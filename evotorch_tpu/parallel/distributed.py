"""Multi-host (DCN) initialization and the multi-host dry-run entry.

The reference documents cluster attach via ``ray start --head`` +
``ray.init(address=...)`` (``docs/advanced_usage/ray_cluster.md:1-40``). The
TPU-native equivalent is ``jax.distributed.initialize``: after it, every host
sees the global device set and the same SPMD programs (GSPMD jit/shard_map)
span hosts, with collectives riding ICI within a slice and DCN across slices.

``dryrun_multihost`` is the runnable proof: each participating process runs
the SAME GSPMD generation program (``parallel.make_generation_step``) over a
mesh spanning every host's devices and prints one JSON line of mesh-global
reductions — identical on every host, and identical to a single-host run of
the same global shape (``tests/test_multihost.py`` spawns 2x4-virtual-device
CPU processes and checks both). CLI form::

    python -m evotorch_tpu.parallel.distributed \
        --coordinator localhost:9999 --num-processes 2 --process-id 0
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["dryrun_multihost", "init_distributed"]

# reductions of SHARDED generation outputs (the scores) must happen on
# device under multi-host — their replicated results are then fetchable on
# every host (device_get refuses arrays spanning non-addressable devices)
_mean_fn = jax.jit(jnp.mean)
_norm_fn = jax.jit(jnp.linalg.norm)


def init_distributed(
    coordinator_address: Optional[str] = None,
    *,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize multi-host JAX if the environment calls for it.

    With no arguments, initialization is attempted only when the standard
    cluster environment variables are present (e.g. on Cloud TPU pods, GKE
    with the JAX plugin, or SLURM); single-host runs return False untouched.
    """
    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and jax.distributed.is_initialized():
        return True
    # Multi-process SPMD on the CPU backend needs a cross-process
    # collectives implementation; the default ("none") makes EVERY
    # multiprocess computation fail to compile ("Multiprocess computations
    # aren't implemented on the CPU backend"). gloo needs the distributed
    # client, so the flag may only be set when initialize() will actually
    # run (with it set but no client, CPU backend creation itself fails) —
    # and it must be set before the first backend use, which is why it
    # lives here and not in callers. Inert on TPU.
    def _enable_cpu_collectives():
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # graftlint: allow(swallow): a jax without the option: CPU multi-process unsupported anyway
            pass  # a jax without the option: CPU multi-process unsupported

    # the handshake retries with bounded backoff (resilience.retry): the
    # usual first-boot race — this process dials before the coordinator
    # binds its port — is a transient RuntimeError/OSError, not a config
    # error, and should not kill a pod job that would succeed 200ms later
    from ..resilience.retry import retry_call

    if coordinator_address is not None:
        _enable_cpu_collectives()
        retry_call(
            jax.distributed.initialize,
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            site="distributed.init",
            retries=5,
            base_delay=0.2,
            max_delay=5.0,
            exceptions=(OSError, RuntimeError),
        )
        return True
    cluster_hints = ("COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS")
    if any(h in os.environ for h in cluster_hints):
        _enable_cpu_collectives()
        retry_call(
            jax.distributed.initialize,
            site="distributed.init",
            retries=5,
            base_delay=0.2,
            max_delay=5.0,
            exceptions=(OSError, RuntimeError),
        )
        return True
    return False


def dryrun_multihost(
    *,
    popsize: int = 64,
    episode_length: int = 20,
    generations: int = 2,
    env_name: str = "cartpole",
    eval_mode: str = "budget",
    seed: int = 0,
) -> dict:
    """Run a few GSPMD generations over the GLOBAL (multi-host) mesh and
    return the mesh-global scalars every host agrees on.

    Must be called AFTER ``init_distributed`` (or on a single host, where it
    degrades to the local device set). The mesh spans ``jax.devices()`` —
    the global device list — so the jitted generation program is one SPMD
    computation across all hosts; per-host Python only feeds keys and reads
    back fully-replicated reductions.
    """
    import numpy as np

    from ..algorithms.functional import pgpe, pgpe_ask, pgpe_tell
    from ..envs import make_env
    from ..neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from ..neuroevolution.net.runningnorm import RunningNorm
    from .evaluate import make_generation_step
    from .mesh import default_mesh, mesh_label

    def replicated(x):
        # a fully-replicated output is the same on every shard, so the
        # first addressable one IS the global value
        if hasattr(x, "addressable_data"):
            return np.asarray(x.addressable_data(0))
        return np.asarray(x)

    env = make_env(env_name)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    mesh = default_mesh(("pop",))  # jax.devices() is the GLOBAL list

    generation = make_generation_step(
        env,
        policy,
        ask=lambda k, s: pgpe_ask(k, s, popsize=popsize),
        tell=pgpe_tell,
        popsize=popsize,
        mesh=mesh,
        num_episodes=1,
        episode_length=episode_length,
        eval_mode=eval_mode,
    )

    state = pgpe(
        center_init=jax.numpy.zeros(policy.parameter_count),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )
    stats = RunningNorm(env.observation_size).stats
    key = jax.random.key(seed)
    total_steps = 0
    mean_score = 0.0
    for _ in range(int(generations)):
        key, sub = jax.random.split(key)
        state, scores, stats, steps, _telemetry = generation(state, sub, stats)
        total_steps += int(replicated(steps))
        mean_score = float(replicated(_mean_fn(scores)))
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "mesh": mesh_label(mesh),
        "devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "popsize": popsize,
        "generations": int(generations),
        "total_steps": total_steps,
        "mean_score": round(mean_score, 6),
        # the updated distribution rides fully replicated: its norm is a
        # cheap cross-host agreement probe on the whole tell pipeline
        "stdev_norm": round(float(replicated(_norm_fn(state.stdev))), 6),
    }


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--coordinator", default=None, help="host:port of process 0")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--popsize", type=int, default=64)
    parser.add_argument("--episode-length", type=int, default=20)
    parser.add_argument("--generations", type=int, default=2)
    parser.add_argument("--env", default="cartpole")
    parser.add_argument("--eval-mode", default="budget")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    init_distributed(
        args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    out = dryrun_multihost(
        popsize=args.popsize,
        episode_length=args.episode_length,
        generations=args.generations,
        env_name=args.env,
        eval_mode=args.eval_mode,
        seed=args.seed,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
