"""Multi-host (DCN) initialization.

The reference documents cluster attach via ``ray start --head`` +
``ray.init(address=...)`` (``docs/advanced_usage/ray_cluster.md:1-40``). The
TPU-native equivalent is ``jax.distributed.initialize``: after it, every host
sees the global device set and the same SPMD programs (shard_map/pjit) span
hosts, with collectives riding ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["init_distributed"]


def init_distributed(
    coordinator_address: Optional[str] = None,
    *,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize multi-host JAX if the environment calls for it.

    With no arguments, initialization is attempted only when the standard
    cluster environment variables are present (e.g. on Cloud TPU pods, GKE
    with the JAX plugin, or SLURM); single-host runs return False untouched.
    """
    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and jax.distributed.is_initialized():
        return True
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    cluster_hints = ("COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS")
    if any(h in os.environ for h in cluster_hints):
        jax.distributed.initialize()
        return True
    return False
