"""Sharded population evaluation.

Replaces the reference's actor-pool fitness evaluation
(``core.py:2573-2600``: split batch -> ``ActorPool.map_unordered`` ->
scatter-back) with a single jitted ``shard_map``: the ``(N, L)`` population is
sharded along the mesh's population axis, each device evaluates its rows
locally, and the sharded result is reassembled by XLA — no pickling, no RPC.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import default_mesh

__all__ = ["make_sharded_evaluator", "shard_population"]


def shard_population(values: jnp.ndarray, mesh: Optional[Mesh] = None, axis_name: str = "pop") -> jnp.ndarray:
    """Place a population array so its leading (population) axis is sharded
    over the mesh — rows live distributed in HBM across devices."""
    if mesh is None:
        mesh = default_mesh((axis_name,))
    return jax.device_put(values, NamedSharding(mesh, P(axis_name)))


def make_sharded_evaluator(
    fitness_func: Callable,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
) -> Callable:
    """Wrap a vectorized fitness function ``f(values (n,L)) -> (n,) | (n,K)``
    into a jitted evaluator that shards the population axis over the mesh.

    Populations whose size is not divisible by the mesh axis are padded with
    their first row and the padding results are discarded (the analog of the
    reference's uneven ``split_workload``, ``tools/misc.py:1113``).
    """
    if mesh is None:
        mesh = default_mesh((axis_name,))
    n_shards = mesh.shape[axis_name]

    def local_eval(values_shard):
        return fitness_func(values_shard)

    @jax.jit
    def evaluator(values):
        n = values.shape[0]
        padded_n = -(-n // n_shards) * n_shards
        if padded_n != n:
            # pad with copies of the first row: always a VALID genome, so
            # fitness functions undefined at synthetic points (log/div at the
            # zero vector) and jax_debug_nans stay safe; the padded results
            # are discarded below
            pad = jnp.broadcast_to(values[:1], (padded_n - n,) + values.shape[1:])
            padded = jnp.concatenate([values, pad], axis=0)
        else:
            padded = values

        out_struct = jax.eval_shape(fitness_func, padded)
        out_specs = jax.tree_util.tree_map(lambda _: P(axis_name), out_struct)
        result = jax.shard_map(
            local_eval,
            mesh=mesh,
            in_specs=P(axis_name),
            out_specs=out_specs,
            check_vma=False,
        )(padded)
        return jax.tree_util.tree_map(lambda r: r[:n], result)

    return evaluator
