"""Sharded population evaluation.

Replaces the reference's actor-pool fitness evaluation
(``core.py:2573-2600``: split batch -> ``ActorPool.map_unordered`` ->
scatter-back) with a single jitted ``shard_map``: the ``(N, L)`` population is
sharded along the mesh's population axis, each device evaluates its rows
locally, and the sharded result is reassembled by XLA — no pickling, no RPC.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import default_mesh

# compiled shard_map programs kept per (lowrank, popsize); matches the spirit
# of vecrl's _ENGINE_CACHE_SIZE bound
_EVALUATOR_CACHE_SIZE = 64

__all__ = [
    "make_sharded_evaluator",
    "make_sharded_rollout_evaluator",
    "shard_population",
]


def shard_population(values: jnp.ndarray, mesh: Optional[Mesh] = None, axis_name: str = "pop") -> jnp.ndarray:
    """Place a population array so its leading (population) axis is sharded
    over the mesh — rows live distributed in HBM across devices."""
    if mesh is None:
        mesh = default_mesh((axis_name,))
    return jax.device_put(values, NamedSharding(mesh, P(axis_name)))


def make_sharded_evaluator(
    fitness_func: Callable,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
) -> Callable:
    """Wrap a vectorized fitness function ``f(values (n,L)) -> (n,) | (n,K)``
    into a jitted evaluator that shards the population axis over the mesh.

    Populations whose size is not divisible by the mesh axis are padded with
    their first row and the padding results are discarded (the analog of the
    reference's uneven ``split_workload``, ``tools/misc.py:1113``).
    """
    if mesh is None:
        mesh = default_mesh((axis_name,))
    n_shards = mesh.shape[axis_name]

    def local_eval(values_shard):
        return fitness_func(values_shard)

    @jax.jit
    def evaluator(values):
        n = values.shape[0]
        padded_n = -(-n // n_shards) * n_shards
        if padded_n != n:
            # pad with copies of the first row: always a VALID genome, so
            # fitness functions undefined at synthetic points (log/div at the
            # zero vector) and jax_debug_nans stay safe; the padded results
            # are discarded below
            pad = jnp.broadcast_to(values[:1], (padded_n - n,) + values.shape[1:])
            padded = jnp.concatenate([values, pad], axis=0)
        else:
            padded = values

        out_struct = jax.eval_shape(fitness_func, padded)
        out_specs = jax.tree_util.tree_map(lambda _: P(axis_name), out_struct)
        result = jax.shard_map(
            local_eval,
            mesh=mesh,
            in_specs=P(axis_name),
            out_specs=out_specs,
            check_vma=False,
        )(padded)
        return jax.tree_util.tree_map(lambda r: r[:n], result)

    return evaluator


def make_sharded_rollout_evaluator(
    env,
    policy,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
    stats_sync: bool = False,
    **rollout_kwargs,
):
    """Shard_map the monolithic rollout engine
    (``neuroevolution.net.vecrl.run_vectorized_rollout``) over the mesh's
    population axis — the reusable form of the sharded-evaluation recipe
    (``dryrun_multichip`` calls it; ``VecNE._evaluate_all`` and
    ``bench_multichip`` still carry historical inline copies of the same
    wiring — keep the three in sync until they migrate here):

    - per-lane PRNG chains seeded by GLOBAL lane ids with the same base key
      on every shard (the mesh is an execution detail);
    - per-shard work queues for ``eval_mode="episodes_refill"``
      (``seed_stride`` is forced to the global popsize so (solution, episode)
      seeds stay unique across shards, and ``refill_width`` is GLOBAL —
      divided across the mesh like every other surface of the knob
      (``VecNE`` ``refill_config['width']``, ``BENCH_REFILL_WIDTH``) —
      so the same value means the same total lane count at any mesh size.
      This helper is the strict surface: it raises on a width not divisible
      by the mesh axis size, while the convenience knobs floor per shard
      like compact_config's widths. With NO explicit width, the tuned-config
      cache (``observability/timings.py``) is consulted per popsize — the
      autotuner's measured winner for this (env, popsize, episode length/count, params, dtype, machine) — and
      ``evaluator.tuned_config_source`` reports the branch taken:
      override / cache / fallback);
    - obs-norm statistics merged with a psum — per-step deltas when
      ``stats_sync=True`` (mesh-global cohort), else one end-of-rollout delta
      merge (shard-local cohorts, the reference's per-actor semantics);
    - step/episode counters psum'd, per-shard counted steps returned;
    - the packed observability telemetry vector psum'd to its mesh-global
      form (all slots additive — ``observability.devicemetrics``), returned
      in ``RolloutResult.telemetry``.

    Accepts dense ``(N, L)`` populations and factored
    ``LowRankParamsBatch``es (coefficients shard; center/basis replicate).
    Returns ``evaluator(values, key, stats) -> (RolloutResult,
    per_shard_steps)``.
    """
    # imported lazily: parallel.* must stay importable before neuroevolution
    from ..neuroevolution.net.vecrl import (
        _params_popsize,
        _params_shard_spec,
        global_lane_ids,
        run_vectorized_rollout,
        RolloutResult,
    )
    from ..tools.lowrank import LowRankParamsBatch

    reserved = {"lane_ids", "stats_sync_axis", "seed_stride"} & set(rollout_kwargs)
    if reserved:
        raise ValueError(
            f"make_sharded_rollout_evaluator sets {sorted(reserved)} itself "
            "(global lane ids, the stats_sync/axis wiring, and the global "
            "seed stride are what the helper exists to get right) — drop "
            "them from the rollout kwargs"
        )
    if mesh is None:
        mesh = default_mesh((axis_name,))
    refill_mode = rollout_kwargs.get("eval_mode") == "episodes_refill"
    # GROUP-level override semantics, same as resolve_knobs everywhere
    # else: ANY explicit refill knob (width OR period) disables the cache
    # for the whole group — a cached width was measured at its cached
    # period, so mixing it with a caller's period would be an unmeasured
    # combination wearing a "cache" label
    explicit_refill = refill_mode and (
        rollout_kwargs.get("refill_width") is not None
        or rollout_kwargs.get("refill_period") is not None
    )
    if refill_mode and rollout_kwargs.get("refill_width") is not None:
        width = int(rollout_kwargs["refill_width"])
        n_shards = mesh.shape[axis_name]
        if width % n_shards != 0:
            raise ValueError(
                f"refill_width={width} is global and must be divisible by "
                f"the mesh axis size {n_shards}"
            )
        rollout_kwargs["refill_width"] = width // n_shards

    def build(lowrank: bool, popsize: int):
        # tuned-config cache (observability/timings.py): a refill
        # evaluation with NO explicit width consults the checked-in
        # tuned_configs.json for this (env, popsize, episode length/count, params, dtype, machine) — cache
        # widths are GLOBAL, divided per shard with the convenience-knob
        # flooring (only an explicit width gets the strict divisibility
        # check above). Provenance: `evaluator.tuned_config_source`.
        local_kwargs = dict(rollout_kwargs)
        source = None
        if refill_mode:
            from ..observability.timings import (
                SOURCE_CACHE,
                SOURCE_FALLBACK,
                SOURCE_OVERRIDE,
                canonical_env_label,
                dtype_label,
                lookup_tuned,
            )

            if explicit_refill:
                source = SOURCE_OVERRIDE
            else:
                entry = lookup_tuned(
                    "refill",
                    {
                        "env": canonical_env_label(env),
                        "popsize": popsize,
                        "episode_length": rollout_kwargs.get("episode_length"),
                        "num_episodes": rollout_kwargs.get("num_episodes", 1),
                        "params": policy.parameter_count,
                        "dtype": dtype_label(rollout_kwargs.get("compute_dtype")),
                    },
                )
                if entry is not None and entry.config.get("width") is not None:
                    n_shards = mesh.shape[axis_name]
                    local_kwargs["refill_width"] = max(
                        1, int(entry.config["width"]) // n_shards
                    )
                    if entry.config.get("period") is not None:
                        local_kwargs["refill_period"] = int(entry.config["period"])
                    source = SOURCE_CACHE
                else:
                    source = SOURCE_FALLBACK

        def local(values_shard, key, stats):
            result = run_vectorized_rollout(
                env,
                policy,
                values_shard,
                key,
                stats,
                lane_ids=global_lane_ids(axis_name, _params_popsize(values_shard)),
                stats_sync_axis=axis_name if stats_sync else None,
                seed_stride=popsize,
                **local_kwargs,
            )
            if stats_sync:
                merged = result.stats  # per-step psums already mesh-global
            else:
                delta = jax.tree_util.tree_map(
                    lambda new, old: new - old, result.stats, stats
                )
                merged = jax.tree_util.tree_map(
                    lambda old, d: old + jax.lax.psum(d, axis_name), stats, delta
                )
            if result.telemetry is None:
                telemetry = jnp.zeros((0,), dtype=jnp.int32)
            else:
                # all telemetry slots are additive: the mesh-global
                # observability vector is one psum, in the same program
                telemetry = jax.lax.psum(result.telemetry, axis_name)
            return (
                result.scores,
                merged,
                jax.lax.psum(result.total_steps, axis_name),
                jax.lax.psum(result.total_episodes, axis_name),
                result.total_steps[None],
                telemetry,
            )

        values_spec = _params_shard_spec(lowrank, axis_name)
        fn = jax.jit(
            jax.shard_map(
                local,
                mesh=mesh,
                in_specs=(values_spec, P(), P()),
                out_specs=(P(axis_name), P(), P(), P(), P(axis_name), P()),
                check_vma=False,
            )
        )
        return fn, source

    # bounded LRU like vecrl's engine caches: an adaptive-popsize caller
    # compiles one shard_map program per distinct popsize, and compiled
    # executables must not accumulate without bound over a long run
    build = functools.lru_cache(maxsize=_EVALUATOR_CACHE_SIZE)(build)

    def evaluator(values, key, stats):
        lowrank = isinstance(values, LowRankParamsBatch)
        popsize = _params_popsize(values)
        fn, source = build(lowrank, popsize)
        evaluator.tuned_config_source = source
        scores, merged, steps, episodes, per_shard, telemetry = fn(values, key, stats)
        result = RolloutResult(
            scores=scores,
            stats=merged,
            total_steps=steps,
            total_episodes=episodes,
            telemetry=telemetry if telemetry.size else None,
        )
        return result, per_shard

    # the jitted (lowrank, popsize) -> shard_map program factory, exposed so
    # the program ledger can AOT-lower the exact executable the evaluator
    # dispatches (observability/inventory.py)
    evaluator.program_builder = lambda lowrank, popsize: build(lowrank, popsize)[0]
    # provenance of the LAST dispatched popsize's refill knobs ("override" /
    # "cache" / "fallback"; None before the first refill-mode dispatch)
    evaluator.tuned_config_source = None
    return evaluator
