"""Sharded population evaluation.

Replaces the reference's actor-pool fitness evaluation
(``core.py:2573-2600``: split batch -> ``ActorPool.map_unordered`` ->
scatter-back) with GSPMD: the evaluation is written ONCE as the global
program, the ``(N, L)`` population is pinned to the mesh's population layout
with ``NamedSharding`` / ``with_sharding_constraint``, and XLA's SPMD
partitioner inserts the collectives — no pickling, no RPC, and no hand-written
per-shard wiring (the per-lane PRNG chains, the obs-stat delta psums and the
counter collectives of the old ``shard_map`` path all become compiler
business). The global program IS the single-device program, so sharded
evaluation is bit-identical to unsharded at any mesh shape (1-D ``pop`` or
2-D ``pop x model``), and popsizes that don't divide the mesh are padded
with first-row copies and masked via the engine's ``num_valid`` contract
(``docs/sharding.md``).

The pre-GSPMD explicit ``shard_map`` path is kept behind
``use_shard_map=True`` / ``EVOTORCH_SHARD_MAP=1`` (the compat knob for A/B
measurement — ``BENCH_SPMD=ab`` in ``bench_multichip.py``; it keeps the old
strict divisibility errors and per-shard cohort semantics).
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import default_mesh, mesh_label, model_axis_size

# compiled programs kept per (params kind, popsize); matches the spirit of
# vecrl's _ENGINE_CACHE_SIZE bound
_EVALUATOR_CACHE_SIZE = 64

__all__ = [
    "make_generation_step",
    "make_resident_rollout_program",
    "make_sharded_evaluator",
    "make_sharded_rollout_evaluator",
    "make_training_span",
    "population_spec",
    "shard_population",
]


def _use_shard_map(flag: Optional[bool]) -> bool:
    """Resolve the compat knob: explicit argument wins, else the
    ``EVOTORCH_SHARD_MAP=1`` environment toggle (default GSPMD)."""
    if flag is None:
        return os.environ.get("EVOTORCH_SHARD_MAP", "0") == "1"
    return bool(flag)


def population_spec(mesh: Mesh) -> P:
    """The canonical ``PartitionSpec`` of a population's leading axis: ALL
    mesh axes flattened onto it — on a 2-D ``pop x model`` mesh the
    population rows spread over the entire device grid (``P(("pop",
    "model"))``), so every device holds whole lanes and the evaluation stays
    bit-identical to the unsharded program (sharding model *parameters*
    across lanes is a different layout with different numerics — see
    docs/sharding.md)."""
    names = tuple(mesh.axis_names)
    return P(names) if len(names) > 1 else P(names[0])


def shard_population(
    values: jnp.ndarray, mesh: Optional[Mesh] = None, axis_name: Optional[str] = None
) -> jnp.ndarray:
    """Place a population array so its leading (population) axis is sharded
    over the mesh — rows live distributed in HBM across devices. With the
    default ``axis_name=None`` the rows spread over ALL mesh axes
    (``population_spec``); passing a name shards over just that axis (the
    historical 1-D form)."""
    if mesh is None:
        mesh = default_mesh((axis_name,) if axis_name is not None else ("pop",))
    spec = population_spec(mesh) if axis_name is None else P(axis_name)
    return jax.device_put(values, NamedSharding(mesh, spec))


def _mesh_grid_size(mesh: Mesh) -> int:
    size = 1
    for s in mesh.shape.values():
        size *= int(s)
    return size


def _pad_rows(values, padded_n: int):
    """Pad a population's leading axis to ``padded_n`` with copies of the
    first row: always a VALID genome, so fitness functions undefined at
    synthetic points (log/div at the zero vector) and jax_debug_nans stay
    safe. Consumers mask the tail via ``num_valid`` or discard it."""
    from ..tools.lowrank import is_factored

    if is_factored(values):
        # per-lane state is the coefficients alone; _replace is
        # type-preserving, so trunk-delta batches keep their factors
        coeffs = values.coeffs
        pad = jnp.broadcast_to(
            coeffs[:1], (padded_n - coeffs.shape[0],) + coeffs.shape[1:]
        )
        return values._replace(coeffs=jnp.concatenate([coeffs, pad], axis=0))
    pad = jnp.broadcast_to(values[:1], (padded_n - values.shape[0],) + values.shape[1:])
    return jnp.concatenate([values, pad], axis=0)


def _constrain_population(values, mesh: Mesh):
    """Pin a (dense or factored) population to the mesh's population layout
    inside a jitted program. Low-rank batches shard their per-lane
    coefficients and replicate the shared center/basis (the factored analog
    of ``vecrl._params_shard_spec``). Trunk-delta batches additionally pin
    their L-sized trunk arrays (flat center + materialized effective basis)
    to the ``model`` axis when the mesh has one — STORAGE sharding (ZeRO
    style): XLA all-gathers the trunk at its use sites, which is
    value-exact, so scores stay bit-identical to the unsharded program
    while the dominant HBM term divides over the model axis
    (``docs/sharding.md``)."""
    from ..tools.lowrank import LowRankParamsBatch, TrunkDeltaParamsBatch

    spec = population_spec(mesh)
    if isinstance(values, TrunkDeltaParamsBatch):
        rep = NamedSharding(mesh, P())
        trunk = (
            NamedSharding(mesh, P(("model",)))
            if model_axis_size(mesh) > 1
            else rep
        )
        return TrunkDeltaParamsBatch(
            center=jax.lax.with_sharding_constraint(values.center, trunk),
            basis=jax.lax.with_sharding_constraint(values.basis, trunk),
            coeffs=jax.lax.with_sharding_constraint(
                values.coeffs, NamedSharding(mesh, spec)
            ),
            factors=jax.tree_util.tree_map(
                lambda f: jax.lax.with_sharding_constraint(f, rep), values.factors
            ),
        )
    if isinstance(values, LowRankParamsBatch):
        rep = NamedSharding(mesh, P())
        return LowRankParamsBatch(
            center=jax.lax.with_sharding_constraint(values.center, rep),
            basis=jax.lax.with_sharding_constraint(values.basis, rep),
            coeffs=jax.lax.with_sharding_constraint(
                values.coeffs, NamedSharding(mesh, spec)
            ),
        )
    return jax.lax.with_sharding_constraint(values, NamedSharding(mesh, spec))


def make_sharded_evaluator(
    fitness_func: Callable,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
    use_shard_map: Optional[bool] = None,
) -> Callable:
    """Wrap a vectorized fitness function ``f(values (n,L)) -> (n,) | (n,K)``
    into a jitted evaluator that shards the population axis over the mesh.

    Populations whose size is not divisible by the mesh are padded with
    their first row and the padding results are discarded (the analog of the
    reference's uneven ``split_workload``, ``tools/misc.py:1113``).

    Default GSPMD: the function is traced once globally and the population is
    pinned to ``population_spec(mesh)`` — XLA partitions the computation.
    ``use_shard_map=True`` (or ``EVOTORCH_SHARD_MAP=1``) keeps the explicit
    per-shard ``shard_map`` form.
    """
    if mesh is None:
        mesh = default_mesh((axis_name,))
    if _use_shard_map(use_shard_map):
        return _shard_map_evaluator(fitness_func, mesh=mesh, axis_name=axis_name)

    n_grid = _mesh_grid_size(mesh)
    sharding = NamedSharding(mesh, population_spec(mesh))

    @jax.jit
    def evaluator(values):
        n = values.shape[0]
        padded_n = -(-n // n_grid) * n_grid
        padded = _pad_rows(values, padded_n) if padded_n != n else values
        padded = jax.lax.with_sharding_constraint(padded, sharding)
        result = fitness_func(padded)
        return jax.tree_util.tree_map(lambda r: r[:n], result)

    return evaluator


def _shard_map_evaluator(fitness_func, *, mesh, axis_name):
    """The pre-GSPMD explicit form (compat knob)."""
    n_shards = mesh.shape[axis_name]

    def local_eval(values_shard):
        return fitness_func(values_shard)

    @jax.jit
    def evaluator(values):
        n = values.shape[0]
        padded_n = -(-n // n_shards) * n_shards
        padded = _pad_rows(values, padded_n) if padded_n != n else values
        out_struct = jax.eval_shape(fitness_func, padded)
        out_specs = jax.tree_util.tree_map(lambda _: P(axis_name), out_struct)
        result = jax.shard_map(
            local_eval,
            mesh=mesh,
            in_specs=P(axis_name),
            out_specs=out_specs,
            check_vma=False,
        )(padded)
        return jax.tree_util.tree_map(lambda r: r[:n], result)

    return evaluator


def _normalize_kind(kind) -> str:
    """Accept the historical boolean ``lowrank`` flag on the
    ``program_builder`` surface and map it onto the kind tags
    (``vecrl._params_kind``): ``False`` -> dense, ``True`` -> lowrank."""
    if isinstance(kind, bool):
        return "lowrank" if kind else "dense"
    return str(kind)


_RESERVED_ROLLOUT_KWARGS = {
    "lane_ids",
    "stats_sync_axis",
    "seed_stride",
    "num_valid",
    "nonfinite_sync_axis",
}


def _check_reserved(rollout_kwargs, what: str):
    reserved = _RESERVED_ROLLOUT_KWARGS & set(rollout_kwargs)
    if reserved:
        raise ValueError(
            f"{what} sets {sorted(reserved)} itself (the global lane/seed "
            "wiring and the padding mask are what the helper exists to get "
            "right) — drop them from the rollout kwargs"
        )


def _lookup_refill_config(env, policy, mesh, rollout_kwargs, popsize):
    """Tuned-config cache consult (observability/timings.py) for a
    refill-mode evaluation with no explicit knobs. Returns
    ``(local_kwargs, source)``. Cache widths are GLOBAL lane counts; the
    lookup shape carries the mesh label, so a schedule tuned at one mesh
    shape is never applied under another (docs/observability.md)."""
    from ..observability.timings import (
        SOURCE_CACHE,
        SOURCE_FALLBACK,
        SOURCE_OVERRIDE,
        canonical_env_label,
        dtype_label,
        lookup_tuned,
    )

    local_kwargs = dict(rollout_kwargs)
    # GROUP-level override semantics, same as resolve_knobs everywhere else:
    # ANY explicit refill knob (width OR period) disables the cache for the
    # whole group — a cached width was measured at its cached period, so
    # mixing it with a caller's period would be an unmeasured combination
    # wearing a "cache" label
    if (
        rollout_kwargs.get("refill_width") is not None
        or rollout_kwargs.get("refill_period") is not None
    ):
        return local_kwargs, SOURCE_OVERRIDE
    entry = lookup_tuned(
        "refill",
        {
            "env": canonical_env_label(env),
            "popsize": popsize,
            "episode_length": rollout_kwargs.get("episode_length"),
            "num_episodes": rollout_kwargs.get("num_episodes", 1),
            "params": policy.parameter_count,
            "dtype": dtype_label(rollout_kwargs.get("compute_dtype")),
            "mesh": mesh_label(mesh),
        },
    )
    if entry is not None and entry.config.get("width") is not None:
        local_kwargs["refill_width"] = int(entry.config["width"])
        if entry.config.get("period") is not None:
            local_kwargs["refill_period"] = int(entry.config["period"])
        return local_kwargs, SOURCE_CACHE
    return local_kwargs, SOURCE_FALLBACK


def make_resident_rollout_program(
    env,
    policy,
    *,
    mesh: Optional[Mesh] = None,
    **rollout_kwargs,
):
    """A long-lived handle on ONE compiled ``episodes_refill`` rollout
    program — the serving substrate (``evotorch_tpu.serving``,
    docs/serving.md).

    Everything that would retrace — the env, the policy shape, the eval
    contract, the lane width/period, the group-row count, the mesh layout —
    is fixed here, at handle construction; every per-dispatch quantity that
    changes as tenants come and go — the packed parameter slab, the
    per-solution base keys (``solution_keys``), the owner-local
    ``lane_ids``, the tenant→group binding (``groups``), the obs-norm
    stats — is TRACED, so admission/departure churn re-dispatches the same
    resident executable (steady_compiles == 0; the retrace sentinel
    enforces it in the serving tests).

    With a ``mesh``, the slab is pinned to ``population_spec(mesh)`` inside
    the program (GSPMD — the global program is the unsharded program, so
    packing semantics and scores are mesh-independent). Call as
    ``program(values, key, stats, lane_ids, groups, solution_keys)``;
    ``program.key`` is the residency identity, ``program.dispatches``
    counts calls."""
    from ..neuroevolution.net.vecrl import run_vectorized_rollout

    rollout_kwargs.setdefault("eval_mode", "episodes_refill")
    if rollout_kwargs["eval_mode"] != "episodes_refill":
        raise ValueError(
            "make_resident_rollout_program serves the episodes_refill"
            f" contract only, got eval_mode={rollout_kwargs['eval_mode']!r}"
        )

    def _run(values, key, stats, lane_ids, groups, solution_keys):
        if mesh is not None:
            values = _constrain_population(values, mesh)
        return run_vectorized_rollout(
            env,
            policy,
            values,
            key,
            stats,
            lane_ids=lane_ids,
            groups=groups,
            solution_keys=solution_keys,
            **rollout_kwargs,
        )

    # one closure-jitted program: no static arguments at THIS layer means
    # the only thing that can retrace is an aval change — exactly the
    # residency contract (slab shape fixed ⇒ executable fixed)
    fn = jax.jit(_run)

    def program(values, key, stats, lane_ids, groups, solution_keys):
        program.dispatches += 1
        return fn(values, key, stats, lane_ids, groups, solution_keys)

    from ..observability.timings import canonical_env_label, dtype_label

    program.dispatches = 0
    program.key = (
        canonical_env_label(env),
        int(policy.parameter_count),
        str(rollout_kwargs["eval_mode"]),
        rollout_kwargs.get("refill_width"),
        mesh_label(mesh) if mesh is not None else "none",
        dtype_label(rollout_kwargs.get("compute_dtype")),
    )
    return program


def make_sharded_rollout_evaluator(
    env,
    policy,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
    stats_sync: bool = False,
    use_shard_map: Optional[bool] = None,
    **rollout_kwargs,
):
    """Shard the monolithic rollout engine
    (``neuroevolution.net.vecrl.run_vectorized_rollout``) over the mesh —
    the reusable form of the sharded-evaluation recipe (``dryrun_multichip``
    and ``VecNE._evaluate_all`` call it; ``bench_multichip`` carries the A/B
    harness over both forms).

    Default GSPMD: the GLOBAL rollout program is jitted once, the population
    pinned to ``population_spec(mesh)`` (all mesh axes flattened over the
    population rows), and XLA partitions the loop — the program IS the
    unsharded program, so scores are bit-identical to single-device at any
    mesh shape, the obs-norm cohort is always the mesh-GLOBAL population
    (``stats_sync`` is moot here: per-shard cohorts were an artifact of the
    explicit per-shard wiring), and popsizes that don't divide the mesh are
    padded with first-row copies whose lanes are masked out of score credit
    and every counter/telemetry slot via the engine's ``num_valid`` contract.

    ``use_shard_map=True`` (or ``EVOTORCH_SHARD_MAP=1``) selects the
    pre-GSPMD explicit path: per-shard ``run_vectorized_rollout`` calls with
    GLOBAL lane ids, psum'd stat deltas/counters/telemetry, per-shard refill
    queues (``refill_width`` divided across the 1-D mesh; raises when an
    explicit width is not divisible), ``stats_sync`` selecting per-step vs
    end-of-rollout stat merges, and strict popsize divisibility.

    Refill evaluations with NO explicit knobs consult the tuned-config cache
    (``observability/timings.py``) per popsize — the autotuner's measured
    winner for this (env, popsize, episode length/count, params, dtype,
    mesh label, machine) — and ``evaluator.tuned_config_source`` reports the
    branch taken: override / cache / fallback.

    Accepts dense ``(N, L)`` populations and factored
    ``LowRankParamsBatch``es (coefficients shard; center/basis replicate) or
    ``TrunkDeltaParamsBatch``es (coefficients shard over the population
    layout; the L-sized trunk arrays storage-shard over the ``model`` axis
    when the mesh has one — see ``_constrain_population``). Returns
    ``evaluator(values, key, stats) -> (RolloutResult, per_shard_steps)``.
    """
    _check_reserved(rollout_kwargs, "make_sharded_rollout_evaluator")
    if mesh is None:
        mesh = default_mesh((axis_name,))
    if _use_shard_map(use_shard_map):
        return _shard_map_rollout_evaluator(
            env,
            policy,
            mesh=mesh,
            axis_name=axis_name,
            stats_sync=stats_sync,
            **rollout_kwargs,
        )

    # imported lazily: parallel.* must stay importable before neuroevolution
    from ..neuroevolution.net.vecrl import (
        _params_kind,
        _params_popsize,
        run_vectorized_rollout,
        RolloutResult,
    )
    from ..observability.devicemetrics import (
        append_health_block,
        compute_health_block,
    )

    n_grid = _mesh_grid_size(mesh)
    refill_mode = rollout_kwargs.get("eval_mode") == "episodes_refill"

    def build(kind: str, popsize: int):
        local_kwargs = dict(rollout_kwargs)
        source = None
        if refill_mode:
            local_kwargs, source = _lookup_refill_config(
                env, policy, mesh, rollout_kwargs, popsize
            )
        padded_n = -(-popsize // n_grid) * n_grid
        num_valid = popsize if padded_n != popsize else None
        # per-group telemetry (ISSUE 15): the groups array is a build-time
        # constant (one id per GENUINE solution); padding rows are
        # first-row copies, so they charge row 0's group — and being
        # permanently inactive, their only charge is capacity, exactly the
        # v1 physical-lane accounting
        groups = local_kwargs.pop("groups", None)
        num_groups = int(local_kwargs.pop("num_groups", 1) or 1)
        groups_valid = (
            jnp.asarray(groups, dtype=jnp.int32)[:popsize]
            if groups is not None and num_groups > 1
            else None
        )
        if groups is not None and num_groups > 1:
            g = jnp.asarray(groups, dtype=jnp.int32)
            if padded_n != popsize:
                g = jnp.concatenate(
                    [g, jnp.broadcast_to(g[:1], (padded_n - popsize,))]
                )
            local_kwargs["groups"] = g
            local_kwargs["num_groups"] = num_groups
        # the search-health block is computed HERE, not inside the engine:
        # replicating the final scores first forces every device to run the
        # identical full-population reduction (no per-shard partial sums),
        # which is what keeps the float32 stats bit-identical across mesh
        # shapes (docs/observability.md "Search health")
        health = bool(local_kwargs.pop("health", True))
        local_kwargs["health"] = False

        def global_eval(values, key, stats):
            if padded_n != popsize:
                values = _pad_rows(values, padded_n)
            values = _constrain_population(values, mesh)
            result = run_vectorized_rollout(
                env,
                policy,
                values,
                key,
                stats,
                num_valid=num_valid,
                **local_kwargs,
            )
            if result.telemetry is None:
                telemetry = jnp.zeros((0,), dtype=jnp.int32)
            else:
                telemetry = result.telemetry  # the global program's counters
                if health:
                    rep = jax.lax.with_sharding_constraint(
                        result.scores, NamedSharding(mesh, P())
                    )
                    telemetry = append_health_block(
                        telemetry,
                        compute_health_block(
                            rep[:popsize],
                            groups_valid,
                            num_groups if groups_valid is not None else 1,
                        ),
                    )
            return (
                result.scores[:popsize],
                result.stats,
                result.total_steps,
                result.total_episodes,
                # GSPMD has no per-shard accounting (XLA owns the layout);
                # the 1-element form keeps the (result, per_shard) contract
                result.total_steps[None],
                telemetry,
            )

        return jax.jit(global_eval), source

    # bounded LRU like vecrl's engine caches: an adaptive-popsize caller
    # compiles one program per distinct popsize, and compiled executables
    # must not accumulate without bound over a long run
    build = functools.lru_cache(maxsize=_EVALUATOR_CACHE_SIZE)(build)

    def evaluator(values, key, stats):
        popsize = _params_popsize(values)
        fn, source = build(_params_kind(values), popsize)
        evaluator.tuned_config_source = source
        scores, merged, steps, episodes, per_shard, telemetry = fn(values, key, stats)
        result = RolloutResult(
            scores=scores,
            stats=merged,
            total_steps=steps,
            total_episodes=episodes,
            telemetry=telemetry if telemetry.size else None,
        )
        return result, per_shard

    # the jitted (kind, popsize) -> program factory, exposed so the program
    # ledger can AOT-lower the exact executable the evaluator dispatches
    # (observability/inventory.py); accepts the historical boolean lowrank
    # flag or a kind tag ("dense"/"lowrank"/"trunk_delta")
    evaluator.program_builder = lambda kind, popsize: build(
        _normalize_kind(kind), popsize
    )[0]
    # provenance of the LAST dispatched popsize's refill knobs ("override" /
    # "cache" / "fallback"; None before the first refill-mode dispatch)
    evaluator.tuned_config_source = None
    return evaluator


def _shard_map_rollout_evaluator(
    env,
    policy,
    *,
    mesh,
    axis_name: str = "pop",
    stats_sync: bool = False,
    **rollout_kwargs,
):
    """The pre-GSPMD explicit shard_map path (compat knob; see
    ``make_sharded_rollout_evaluator``)."""
    from ..neuroevolution.net.vecrl import (
        _params_kind,
        _params_popsize,
        _params_shard_spec,
        global_lane_ids,
        run_vectorized_rollout,
        RolloutResult,
    )

    refill_mode = rollout_kwargs.get("eval_mode") == "episodes_refill"
    if refill_mode and rollout_kwargs.get("refill_width") is not None:
        width = int(rollout_kwargs["refill_width"])
        n_shards = mesh.shape[axis_name]
        if width % n_shards != 0:
            raise ValueError(
                f"refill_width={width} is global and must be divisible by "
                f"the mesh axis size {n_shards}"
            )
        rollout_kwargs["refill_width"] = width // n_shards

    # per-group telemetry rides in as an explicit 4th sharded input: each
    # shard segment-sums over its local lanes and the additive (G, K) block
    # psums mesh-global like every other telemetry slot
    groups_global = rollout_kwargs.pop("groups", None)
    num_groups = int(rollout_kwargs.pop("num_groups", 1) or 1)
    collect_groups = groups_global is not None and num_groups > 1
    if collect_groups:
        groups_global = jnp.asarray(groups_global, dtype=jnp.int32)

    # non-finite quarantine on this explicit path: the worst-finite
    # reduction must pmin over the mesh so the sharded replacement score is
    # the GLOBAL worst finite one (the GSPMD path's reduction is global by
    # construction); a fixed penalty needs no collective
    if (
        rollout_kwargs.get("nonfinite_quarantine")
        and rollout_kwargs.get("nonfinite_penalty") is None
    ):
        rollout_kwargs["nonfinite_sync_axis"] = axis_name

    # the per-shard engine must NOT append its own health block — the
    # telemetry psum below would sum the bit-cast float columns across
    # shards into garbage; the local fn all_gathers the scores and appends
    # ONE mesh-global block (shard-0 masked) instead
    health = bool(rollout_kwargs.pop("health", True))
    rollout_kwargs["health"] = False
    from ..observability.devicemetrics import (
        append_health_block,
        compute_health_block,
    )

    def build(kind: str, popsize: int):
        # tuned-config cache: cache widths are GLOBAL, divided per shard with
        # the convenience-knob flooring (only an explicit width gets the
        # strict divisibility check above)
        local_kwargs = dict(rollout_kwargs)
        source = None
        if refill_mode:
            local_kwargs, source = _lookup_refill_config(
                env, policy, mesh, rollout_kwargs, popsize
            )
            from ..observability.timings import SOURCE_CACHE

            if source == SOURCE_CACHE:
                n_shards = mesh.shape[axis_name]
                local_kwargs["refill_width"] = max(
                    1, int(local_kwargs["refill_width"]) // n_shards
                )

        def local(values_shard, key, stats, groups_shard=None):
            result = run_vectorized_rollout(
                env,
                policy,
                values_shard,
                key,
                stats,
                lane_ids=global_lane_ids(axis_name, _params_popsize(values_shard)),
                stats_sync_axis=axis_name if stats_sync else None,
                seed_stride=popsize,
                groups=groups_shard,
                num_groups=num_groups if groups_shard is not None else 1,
                **local_kwargs,
            )
            if stats_sync:
                merged = result.stats  # per-step psums already mesh-global
            else:
                delta = jax.tree_util.tree_map(
                    lambda new, old: new - old, result.stats, stats
                )
                merged = jax.tree_util.tree_map(
                    lambda old, d: old + jax.lax.psum(d, axis_name), stats, delta
                )
            if result.telemetry is None:
                telemetry = jnp.zeros((0,), dtype=jnp.int32)
            else:
                telemetry = result.telemetry
                if health:
                    # mesh-global health block: gather the final scores into
                    # GLOBAL lane order (shards hold contiguous blocks, so
                    # tiled all_gather IS the unsharded order), compute the
                    # identical full-population reduction on every shard,
                    # then zero all but shard 0's copy so the integer psum
                    # carries the bit-cast float columns through exactly
                    g_scores = jax.lax.all_gather(
                        result.scores, axis_name, tiled=True
                    )
                    g_groups = (
                        jax.lax.all_gather(groups_shard, axis_name, tiled=True)
                        if groups_shard is not None
                        else None
                    )
                    block = compute_health_block(
                        g_scores,
                        g_groups,
                        num_groups if groups_shard is not None else 1,
                    )
                    shard0 = (jax.lax.axis_index(axis_name) == 0).astype(
                        block.dtype
                    )
                    telemetry = append_health_block(telemetry, block * shard0)
                # all telemetry slots are additive: the mesh-global
                # observability vector is one psum, in the same program
                telemetry = jax.lax.psum(telemetry, axis_name)
            return (
                result.scores,
                merged,
                jax.lax.psum(result.total_steps, axis_name),
                jax.lax.psum(result.total_episodes, axis_name),
                result.total_steps[None],
                telemetry,
            )

        values_spec = _params_shard_spec(kind, axis_name)
        in_specs = (values_spec, P(), P())
        if collect_groups:
            in_specs = in_specs + (P(axis_name),)
        fn = jax.jit(
            jax.shard_map(
                local,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=(P(axis_name), P(), P(), P(), P(axis_name), P()),
                check_vma=False,
            )
        )
        return fn, source

    build = functools.lru_cache(maxsize=_EVALUATOR_CACHE_SIZE)(build)

    def evaluator(values, key, stats):
        popsize = _params_popsize(values)
        fn, source = build(_params_kind(values), popsize)
        evaluator.tuned_config_source = source
        if collect_groups:
            scores, merged, steps, episodes, per_shard, telemetry = fn(
                values, key, stats, groups_global
            )
        else:
            scores, merged, steps, episodes, per_shard, telemetry = fn(
                values, key, stats
            )
        result = RolloutResult(
            scores=scores,
            stats=merged,
            total_steps=steps,
            total_episodes=episodes,
            telemetry=telemetry if telemetry.size else None,
        )
        return result, per_shard

    evaluator.program_builder = lambda kind, popsize: build(
        _normalize_kind(kind), popsize
    )[0]
    evaluator.tuned_config_source = None
    return evaluator


def _generation_body(
    env,
    policy,
    *,
    ask: Callable,
    tell: Callable,
    popsize: int,
    mesh: Mesh,
    **rollout_kwargs,
):
    """The UNJITTED ``ask -> sharded rollout -> tell`` generation body shared
    by :func:`make_generation_step` (which jits it as-is) and
    :func:`make_training_span` (which ``lax.scan``s it K times inside one
    program). Keeping one body is what makes the span bit-identity guarantee
    structural: the scanned step IS the per-generation step, traced from the
    same closure."""
    from ..neuroevolution.net.vecrl import run_vectorized_rollout
    from ..observability.devicemetrics import (
        append_health_block,
        compute_health_block,
    )

    popsize = int(popsize)
    n_grid = _mesh_grid_size(mesh)
    padded_n = -(-popsize // n_grid) * n_grid
    num_valid = popsize if padded_n != popsize else None
    # per-group telemetry: pad the group-id array exactly like the
    # population rows (first-element copies; see
    # make_sharded_rollout_evaluator)
    groups = rollout_kwargs.pop("groups", None)
    num_groups = int(rollout_kwargs.pop("num_groups", 1) or 1)
    groups_valid = (
        jnp.asarray(groups, dtype=jnp.int32)[:popsize]
        if groups is not None and num_groups > 1
        else None
    )
    if groups is not None and num_groups > 1:
        g = jnp.asarray(groups, dtype=jnp.int32)
        if padded_n != popsize:
            g = jnp.concatenate([g, jnp.broadcast_to(g[:1], (padded_n - popsize,))])
        rollout_kwargs["groups"] = g
        rollout_kwargs["num_groups"] = num_groups
    # health block computed on replicated scores, like
    # make_sharded_rollout_evaluator (mesh-shape bit-identity)
    health = bool(rollout_kwargs.pop("health", True))
    rollout_kwargs["health"] = False

    def generation(state, key, stats):
        k_ask, k_eval = jax.random.split(key)
        values = ask(k_ask, state)
        evald = _pad_rows(values, padded_n) if padded_n != popsize else values
        evald = _constrain_population(evald, mesh)
        result = run_vectorized_rollout(
            env,
            policy,
            evald,
            k_eval,
            stats,
            num_valid=num_valid,
            **rollout_kwargs,
        )
        scores = result.scores[:popsize]
        new_state = tell(state, values, scores)
        if result.telemetry is None:
            telemetry = jnp.zeros((0,), dtype=jnp.int32)
        else:
            telemetry = result.telemetry
            if health:
                rep = jax.lax.with_sharding_constraint(
                    result.scores, NamedSharding(mesh, P())
                )
                telemetry = append_health_block(
                    telemetry,
                    compute_health_block(
                        rep[:popsize],
                        groups_valid,
                        num_groups if groups_valid is not None else 1,
                    ),
                )
        return new_state, scores, result.stats, result.total_steps, telemetry

    return generation


def make_generation_step(
    env,
    policy,
    *,
    ask: Callable,
    tell: Callable,
    popsize: int,
    mesh: Optional[Mesh] = None,
    donate_state: bool = True,
    **rollout_kwargs,
):
    """One whole generation — ``ask -> sharded rollout -> tell`` — compiled
    as ONE jitted GSPMD program with the evolution state DONATED: the
    sample buffers, the rollout working set and the updated distribution
    state all reuse the previous generation's HBM, so a training loop's
    steady-state footprint is a single generation's live set (the program
    ledger's donation verification covers this program;
    ``docs/observability.md``).

    ``ask(key, state) -> values`` samples the ``(popsize, L)`` population
    (dense, ``LowRankParamsBatch``, or ``TrunkDeltaParamsBatch`` — e.g.
    ``pgpe_ask_trunk_delta``); ``tell(state, values, scores) -> state``
    applies the update. Both run INSIDE the program — the population is born
    on its shards, evaluated in place, and consumed by the update without
    ever leaving the device grid.

    Returns ``generation(state, key, stats) -> (state, scores, stats,
    total_steps, telemetry)``. With ``donate_state=True`` (default) the
    caller must rebind: ``state, ... = generation(state, key, stats)`` —
    the old state's buffers are invalidated.
    """
    _check_reserved(rollout_kwargs, "make_generation_step")
    if mesh is None:
        mesh = default_mesh(("pop",))
    generation = _generation_body(
        env, policy, ask=ask, tell=tell, popsize=popsize, mesh=mesh,
        **rollout_kwargs,
    )
    return jax.jit(generation, donate_argnums=(0,) if donate_state else ())


def make_training_span(
    env,
    policy,
    *,
    ask: Callable,
    tell: Callable,
    popsize: int,
    span: int,
    mesh: Optional[Mesh] = None,
    donate_state: bool = True,
    state_metrics: Optional[Callable] = None,
    **rollout_kwargs,
):
    """``span`` generations fused into ONE jitted, state-donating GSPMD
    program: a ``lax.scan`` over the :func:`make_generation_step` body, so a
    training loop pays Python dispatch + device sync + telemetry decode once
    per K generations instead of once per generation (the Podracer/Anakin
    move applied to the ES outer loop; ``docs/sharding.md`` "Fused
    multi-generation training spans").

    ``ask``/``tell``/``popsize``/``mesh``/``rollout_kwargs`` mean exactly
    what they mean for :func:`make_generation_step` — the scanned step is the
    SAME traced body, so the result is bit-identical (state pytree, scores,
    telemetry column sums, obs-norm stats) to ``span`` sequential
    ``make_generation_step`` calls fed the same per-generation keys, at any
    mesh shape including padded indivisible popsizes. The obs-norm ``stats``
    ride the scan carry, preserving the sequential update order.

    ``eval_mode="episodes_compact"`` is rejected: lane compaction is
    host-orchestrated (chunked re-dispatch from Python;
    ``docs/eval_contracts.md``), so it cannot live inside a monolithic
    scanned program — use ``episodes_refill`` for the on-device
    work-conserving form.

    ``state_metrics(state) -> pytree`` (optional, e.g.
    ``algorithms.functional.pgpe_health``) is evaluated on the post-``tell``
    state of EVERY generation inside the program; its stacked outputs let
    hosts reconstruct per-generation algorithm-health rows without K extra
    dispatches.

    Returns ``training_span(state, keys, stats) -> (state, scores, stats,
    total_steps, telemetry[, metrics])`` where ``keys`` is a ``(span,)``
    PRNG key array (one per generation — e.g. ``jax.random.split(key,
    span)``; scan raises at trace time on a length mismatch) and the ys are
    stacked per generation: ``scores (span, popsize)``, ``total_steps
    (span,)``, ``telemetry (span, G, C)`` (or ``(span, 0)`` with telemetry
    off — decode row-by-row, see docs/observability.md "Lag-by-span"), and
    ``metrics`` the stacked ``state_metrics`` pytree when provided. With
    ``donate_state=True`` (default) the caller must rebind ``state``.
    """
    _check_reserved(rollout_kwargs, "make_training_span")
    span = int(span)
    if span < 1:
        raise ValueError(f"span must be >= 1, got {span}")
    if rollout_kwargs.get("eval_mode") == "episodes_compact":
        raise ValueError(
            "make_training_span cannot fuse eval_mode='episodes_compact': "
            "lane compaction is host-orchestrated (chunked re-dispatch from "
            "Python) and cannot run inside one scanned device program — use "
            "'episodes_refill' for the on-device work-conserving contract"
        )
    if mesh is None:
        mesh = default_mesh(("pop",))
    generation = _generation_body(
        env, policy, ask=ask, tell=tell, popsize=popsize, mesh=mesh,
        **rollout_kwargs,
    )

    def training_span(state, keys, stats):
        kshape = jnp.shape(keys)
        if not kshape or kshape[0] != span:
            raise ValueError(
                f"training_span expects a (span={span},) PRNG key array — "
                f"one key per generation, e.g. jax.random.split(key, {span}) "
                f"— got key shape {kshape}"
            )

        def body(carry, key):
            state, stats = carry
            state, scores, stats, steps, telemetry = generation(state, key, stats)
            ys = (scores, steps, telemetry)
            if state_metrics is not None:
                ys = ys + (state_metrics(state),)
            return (state, stats), ys

        (state, stats), ys = jax.lax.scan(body, (state, stats), keys, length=span)
        out = (state, ys[0], stats, ys[1], ys[2])
        if state_metrics is not None:
            out = out + (ys[3],)
        return out

    return jax.jit(training_span, donate_argnums=(0,) if donate_state else ())
