"""Device-mesh helpers.

The mesh is the TPU analog of the reference's actor pool size
(``num_actors``, reference ``core.py:1302-1595``): instead of asking "how many
Ray actors", you ask "which mesh axes". The default is a 1-D mesh named
``"pop"`` over all local devices, used to shard the population axis; 2-D
``pop x model`` meshes add a model axis for sharding wide-policy parameters
(docs/sharding.md).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "MESH_AXES",
    "default_mesh",
    "device_count",
    "make_mesh",
    "mesh_label",
    "model_axis_size",
    "parse_mesh_shape",
]

#: the named mesh axes of the parallel layer (docs/sharding.md): ``"pop"``
#: shards the population axis, ``"model"`` shards model parameters (wide
#: policies) — graftlint's axis-name checker validates collective /
#: PartitionSpec string literals against this declaration
MESH_AXES = ("pop", "model")


def device_count() -> int:
    return jax.device_count()


def default_mesh(axis_names: Sequence[str] = ("pop",), devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    if len(axis_names) != 1:
        raise ValueError("default_mesh creates 1-D meshes; use make_mesh for N-D")
    return Mesh(np.asarray(devices), axis_names=tuple(axis_names))


def make_mesh(axis_shape: dict, devices=None) -> Mesh:
    """N-D mesh from ``{axis_name: size}``; e.g.
    ``make_mesh({"pop": 4, "model": 2})`` lays population-parallel shards over
    4 device groups with 2-way model sharding inside each."""
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_shape.keys())
    shape = tuple(int(s) for s in axis_shape.values())
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(f"Mesh needs {total} devices, but only {len(devices)} are available")
    grid = np.asarray(devices[:total]).reshape(shape)
    return Mesh(grid, axis_names=names)


def mesh_label(mesh: Optional[Mesh]) -> str:
    """The canonical mesh-shape label used in timing-ledger / tuned-config
    cache keys (``observability.timings``): ``"none"`` for an unsharded
    evaluation, ``"pop8"`` for a 1-D 8-way pop mesh, ``"pop4.model2"`` for a
    2-D mesh, with a ``"hosts{n}."`` prefix under multi-host
    (``jax.distributed``). Size-1 axes are dropped — a ``(8, 1)``
    ``pop x model`` mesh lays out identically to a 1-D ``pop`` 8-mesh, so
    measurements transfer — and an all-1 mesh IS the unsharded layout
    (``"none"``). A schedule tuned at one label is never applied under
    another (ISSUE 13 satellite; a width tuned on the 1-D 8-mesh says
    nothing about a 2-D or multi-host layout)."""
    if mesh is None:
        return "none"
    parts = [f"{name}{size}" for name, size in mesh.shape.items() if int(size) > 1]
    label = ".".join(parts) if parts else "none"
    n_hosts = jax.process_count()
    if n_hosts > 1:
        label = f"hosts{n_hosts}.{label}"
    return label


def model_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the mesh's ``model`` axis, 1 when absent (or no mesh): the
    storage-sharding divisor for a trunk-delta population's L-sized trunk
    arrays (``parallel.evaluate._constrain_population``;
    docs/policies.md)."""
    if mesh is None or "model" not in mesh.axis_names:
        return 1
    return int(mesh.shape["model"])


def parse_mesh_shape(spec) -> dict:
    """Parse a mesh-shape knob (``BENCH_MESH``) into ``{axis: size}``:

    - ``"8"`` / ``8``      -> ``{"pop": 8}`` (the historical 1-D form)
    - ``"4x2"``            -> ``{"pop": 4, "model": 2}``
    - ``"pop=4,model=2"``  -> ``{"pop": 4, "model": 2}`` (explicit names)
    """
    if isinstance(spec, int):
        return {"pop": int(spec)}
    text = str(spec).strip()
    if "=" in text:
        out = {}
        for part in text.split(","):
            name, _, size = part.partition("=")
            out[name.strip()] = int(size)
        return out
    if "x" in text:
        sizes = [int(p) for p in text.split("x")]
        if len(sizes) > len(MESH_AXES):
            raise ValueError(
                f"mesh shape {text!r} has {len(sizes)} axes; named axes are {MESH_AXES}"
            )
        return {name: size for name, size in zip(MESH_AXES, sizes)}
    return {"pop": int(text)}
