"""Device-mesh helpers.

The mesh is the TPU analog of the reference's actor pool size
(``num_actors``, reference ``core.py:1302-1595``): instead of asking "how many
Ray actors", you ask "which mesh axes". The default is a 1-D mesh named
``"pop"`` over all local devices, used to shard the population axis.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["default_mesh", "make_mesh", "device_count"]


def device_count() -> int:
    return jax.device_count()


def default_mesh(axis_names: Sequence[str] = ("pop",), devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    if len(axis_names) != 1:
        raise ValueError("default_mesh creates 1-D meshes; use make_mesh for N-D")
    return Mesh(np.asarray(devices), axis_names=tuple(axis_names))


def make_mesh(axis_shape: dict, devices=None) -> Mesh:
    """N-D mesh from ``{axis_name: size}``; e.g.
    ``make_mesh({"pop": 4, "model": 2})`` lays population-parallel shards over
    4 device groups with 2-way model sharding inside each."""
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_shape.keys())
    shape = tuple(int(s) for s in axis_shape.values())
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(f"Mesh needs {total} devices, but only {len(devices)} are available")
    grid = np.asarray(devices[:total]).reshape(shape)
    return Mesh(grid, axis_names=names)
