"""Bounded exponential-backoff retry for fragile host-side ops.

The compiled eval programs are deterministic; the host edges around them —
coordinator handshakes (``jax.distributed.initialize``), compile-cache IO,
MetricsHub writes, worker-pool dispatch — fail for boring transient
reasons (NFS blips, a coordinator that is still binding its port, a dying
actor). This wraps them uniformly:

- bounded attempts with exponential backoff (deterministic delays — no
  jitter, so test timing is reproducible; the delays are host-side sleeps,
  never on the device path),
- :mod:`~evotorch_tpu.observability.registry` counters
  (``retry.<site>.attempts`` / ``.retries`` / ``.giveups``) so a run that
  limped through on retries says so in the counter snapshot,
- a tracer span per retried attempt (``retry:<site>``) so stalls show up
  on the host timeline next to the phase spans,
- a :func:`~evotorch_tpu.resilience.faults.fault_point` at every attempt,
  which makes every retried op fault-injectable for free
  (``EVOTORCH_FAULTS="<site>:raise@1"`` exercises the retry path;
  ``...@1+`` with ``retries`` exceeded exercises the give-up path).
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional, Tuple, Type

from ..observability import tracer
from ..observability.registry import counters
from .faults import fault_point

__all__ = ["retry_call", "retryable"]


def retry_call(
    fn: Callable,
    *args,
    site: str,
    retries: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` with up to ``retries`` retries.

    ``exceptions`` is the retryable set (default ``OSError`` — the IO
    family, which also covers :class:`InjectedFault`); anything else
    propagates immediately. ``on_retry(attempt, exc)`` runs before each
    backoff sleep (hostpool uses it to respawn the dead worker). The final
    failure re-raises the last exception unchanged — retrying is
    transparent, not exception-rewriting.
    """
    attempts = int(retries) + 1
    delay = float(base_delay)
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        counters.increment(f"retry.{site}.attempts")
        try:
            fault_point(site)
            return fn(*args, **kwargs)
        except exceptions as exc:  # noqa: PERF203 — the slow path IS the point
            last = exc
            if attempt >= attempts:
                counters.increment(f"retry.{site}.giveups")
                raise
            counters.increment(f"retry.{site}.retries")
            if on_retry is not None:
                on_retry(attempt, exc)
            with tracer.span(f"retry:{site}", cat="resilience", attempt=attempt,
                             error=type(exc).__name__):
                time.sleep(delay)
            delay = min(delay * 2.0, float(max_delay))
    raise AssertionError(last)  # unreachable


def retryable(
    *,
    site: str,
    retries: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
):
    """Decorator form of :func:`retry_call` for fixed call sites."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(
                fn,
                *args,
                site=site,
                retries=retries,
                base_delay=base_delay,
                max_delay=max_delay,
                exceptions=exceptions,
                **kwargs,
            )

        return wrapped

    return deco
