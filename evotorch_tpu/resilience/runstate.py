"""Durable run checkpoints: versioned bundles a SIGKILL cannot corrupt.

:mod:`evotorch_tpu.checkpoint` has the leaf primitives (orbax pytree
save/load, whole-searcher pickle); what a long run needs is one durable
*bundle* per checkpoint interval carrying everything resume requires —
the searcher (whose pickle transitively contains the functional search
state, PRNG chain, obs-norm statistics and interaction counters), the
generation number, the registry counter snapshot, tuned-config
provenance, the git sha, and a schema version — written so that a crash
at ANY instant leaves the directory loadable:

- **atomic**: payload goes to a tmp file, is fsync'd, then ``os.replace``d
  into place (readers and crashes see either the old bundle set or the
  new one, never a half-written file);
- **self-verifying**: a fixed magic plus the payload's SHA-256 ride in the
  header, so truncation/corruption is *detected* at load, not discovered
  as a confusing unpickling error;
- **redundant**: keep-last-K retention (default 3), and
  :meth:`RunCheckpointer.load_latest` walks bundles newest-first,
  skipping invalid ones (counter ``checkpoint.corrupt_skipped``) — one
  bad bundle costs one interval of progress, not the run.

Because the searcher state is a pure pytree and every stochastic choice
flows from the PRNG key stored inside it, a killed-and-resumed run
replays the uninterrupted run's trajectory **bit-identically**
(tests/test_resilience.py asserts this, including through SIGKILL).

See docs/resilience.md for the bundle format and the resume wiring in
``examples/locomotion_curve.py``.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["RunCheckpointer", "CorruptBundleError", "BUNDLE_SCHEMA_VERSION"]

#: bump when the payload layout changes incompatibly; loaders refuse
#: bundles from a NEWER schema (an older writer cannot know what it means)
BUNDLE_SCHEMA_VERSION = 1

_MAGIC = b"EVTRUNB1"  # 8 bytes: format id + container version
_BUNDLE_RE = re.compile(r"^bundle_(\d{8})\.ckpt$")


class CorruptBundleError(RuntimeError):
    """A bundle file failed magic/digest/schema verification."""


def _git_sha() -> Optional[str]:
    from ..observability.metricshub import _git_sha as sha

    return sha()


class RunCheckpointer:
    """Write/read durable run bundles in a directory.

    ``save(generation, state)`` persists one bundle (``state`` is an
    arbitrary picklable dict — by convention ``{"searcher": searcher,
    ...}``); ``load_latest()`` returns ``(generation, state)`` from the
    newest VALID bundle, or ``None`` on an empty/fully-corrupt directory.
    ``every`` makes ``maybe_save`` a cadence helper so call sites don't
    carry modulo logic.
    """

    def __init__(self, directory: str, *, keep: int = 3, every: int = 1):
        if int(keep) < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if int(every) < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.directory = os.path.abspath(directory)
        self.keep = int(keep)
        self.every = int(every)
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------ write
    def maybe_save(self, generation: int, state: Dict[str, Any]) -> Optional[str]:
        """``save`` when the generation lands on the cadence, else None."""
        if int(generation) % self.every != 0:
            return None
        return self.save(generation, state)

    def save(self, generation: int, state: Dict[str, Any]) -> str:
        """Atomically persist one bundle; returns its path."""
        from ..observability.registry import counters

        payload = pickle.dumps(
            {
                "schema": BUNDLE_SCHEMA_VERSION,
                "generation": int(generation),
                "git_sha": _git_sha(),
                "registry": counters.snapshot(),
                "state": state,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        digest = hashlib.sha256(payload).digest()
        path = os.path.join(self.directory, f"bundle_{int(generation):08d}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(_MAGIC)
            fh.write(digest)
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        counters.increment("checkpoint.bundles_written")
        self._prune()
        return path

    def _prune(self) -> None:
        paths = self.bundle_paths()
        for path in paths[: max(0, len(paths) - self.keep)]:
            try:
                os.remove(path)
            except OSError:  # graftlint: allow(swallow): retention is best-effort; a busy/unlinkable old bundle is harmless
                pass

    # ------------------------------------------------------------------- read
    def bundle_paths(self) -> List[str]:
        """Existing bundle paths, oldest first (by generation)."""
        entries = []
        for name in os.listdir(self.directory):
            m = _BUNDLE_RE.match(name)
            if m:
                entries.append((int(m.group(1)), os.path.join(self.directory, name)))
        return [path for _, path in sorted(entries)]

    @staticmethod
    def read_bundle(path: str) -> Tuple[int, Dict[str, Any]]:
        """Verify + decode one bundle; raises :class:`CorruptBundleError`."""
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as exc:
            raise CorruptBundleError(f"unreadable bundle {path}: {exc}") from exc
        if len(blob) < len(_MAGIC) + 32 or not blob.startswith(_MAGIC):
            raise CorruptBundleError(
                f"{path} is not a run bundle (bad magic or truncated header)"
            )
        digest = blob[len(_MAGIC) : len(_MAGIC) + 32]
        payload = blob[len(_MAGIC) + 32 :]
        if hashlib.sha256(payload).digest() != digest:
            raise CorruptBundleError(
                f"{path} failed its SHA-256 check (truncated or corrupted "
                "write) — falling back to an older bundle is safe"
            )
        try:
            record = pickle.load(io.BytesIO(payload))
        except Exception as exc:
            raise CorruptBundleError(f"{path} payload does not unpickle: {exc}") from exc
        schema = record.get("schema")
        if not isinstance(schema, int) or schema > BUNDLE_SCHEMA_VERSION:
            raise CorruptBundleError(
                f"{path} has bundle schema {schema!r}; this build reads <= "
                f"{BUNDLE_SCHEMA_VERSION}"
            )
        return int(record["generation"]), record["state"]

    def load_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """The newest valid bundle's ``(generation, state)``, else None.

        Invalid bundles are skipped (newest-first) with a counter bump —
        a partial write from the crash that necessitated the resume is the
        expected case, not an exception.
        """
        from ..observability.registry import counters

        for path in reversed(self.bundle_paths()):
            try:
                return self.read_bundle(path)
            except CorruptBundleError:  # graftlint: allow(swallow): counted + fall back to the next-newest bundle — that fallback IS the feature
                counters.increment("checkpoint.corrupt_skipped")
        return None
