"""Deterministic fault injection (``EVOTORCH_FAULTS``).

Recovery code that is never exercised is recovery code that does not work:
this module turns "hope the retry path is right" into tier-1 tests by
injecting *seeded, reproducible* faults at named host-side sites. The spec
grammar (docs/resilience.md) is a semicolon-separated list of entries::

    EVOTORCH_FAULTS="metricshub.write:raise@2;hostpool.worker:kill@1"

Each entry is ``site:kind@N[:arg]``:

``site``
    a dotted fault-site name; code declares sites by calling
    :func:`fault_point` (retry wrappers do it automatically, so every
    retried op is injectable for free).
``kind``
    ``raise``  — raise :class:`InjectedFault` (an ``OSError``, so IO retry
    paths catch it like a real one) at the matching invocation;
    ``sigkill`` — ``SIGKILL`` the current process (the subprocess
    crash-resume harness; nothing survives, by design);
    ``kill`` / ``nonfinite`` / any other word — *advisory*: the fired rule
    is RETURNED to the instrumented site, which interprets it (hostpool
    kills a worker, VecNE corrupts a seeded share of scores, ...).
``@N``
    fire at the N-th invocation of the site (1-based, counted per rule).
    ``@N+`` fires at every invocation from the N-th on.
``arg``
    optional payload (e.g. the score share for ``nonfinite``), kept as a
    string; :meth:`FaultRule.float_arg` parses the common case.

Counting is per-rule and process-local, so a spec fires at the same
invocation in every run — determinism is the point. Tests use
:func:`configure` directly instead of the environment variable.
"""

from __future__ import annotations

import os
import signal
import threading
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = [
    "InjectedFault",
    "FaultRule",
    "parse_spec",
    "configure",
    "active_spec",
    "fault_point",
]

_ENV_VAR = "EVOTORCH_FAULTS"


class InjectedFault(OSError):
    """A fault raised by the injection harness (never by real code)."""


@dataclass
class FaultRule:
    """One parsed ``site:kind@N[:arg]`` entry."""

    site: str
    kind: str
    at: int
    arg: Optional[str] = None
    sticky: bool = False  # "@N+": keep firing from the N-th invocation on
    count: int = field(default=0, repr=False)

    def float_arg(self, default: float) -> float:
        return default if self.arg is None else float(self.arg)


def parse_spec(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            head, at = entry.rsplit("@", 1)
            site, _, kind = head.rpartition(":")
            arg: Optional[str] = None
            if ":" in at:
                at, arg = at.split(":", 1)
            sticky = at.endswith("+")
            if sticky:
                at = at[:-1]
            if not site or not kind:
                raise ValueError(entry)
            rules.append(
                FaultRule(site=site, kind=kind, at=int(at), arg=arg, sticky=sticky)
            )
        except (ValueError, TypeError):
            raise ValueError(
                f"bad {_ENV_VAR} entry {entry!r}; expected 'site:kind@N[:arg]'"
            ) from None
    return rules


_lock = threading.Lock()
_rules: Optional[List[FaultRule]] = None  # None = not yet parsed from env


def configure(spec: Optional[str]) -> None:
    """(Re)configure injection from a spec string (tests), or None to
    re-read ``EVOTORCH_FAULTS`` lazily. Resets all per-rule counters."""
    global _rules
    with _lock:
        _rules = None if spec is None else parse_spec(spec)


def active_spec() -> List[FaultRule]:
    global _rules
    with _lock:
        if _rules is None:
            _rules = parse_spec(os.environ.get(_ENV_VAR, ""))
        return _rules


def fault_point(site: str) -> Optional[FaultRule]:
    """Declare one invocation of a named fault site.

    Counts the invocation against every rule for ``site``; a matching
    ``raise`` rule raises :class:`InjectedFault`, ``sigkill`` kills the
    process, and any other fired rule is returned for the caller to
    interpret (None otherwise — the overwhelmingly common, near-free path:
    no spec means one dict-free loop over an empty list).
    """
    rules = active_spec()
    if not rules:
        return None
    fired: Optional[FaultRule] = None
    with _lock:
        for rule in rules:
            if rule.site != site:
                continue
            rule.count += 1
            if rule.count == rule.at or (rule.sticky and rule.count > rule.at):
                fired = rule
                break
    if fired is None:
        return None
    from ..observability.registry import counters

    counters.increment(f"faults.fired.{site}.{fired.kind}")
    if fired.kind == "raise":
        raise InjectedFault(f"injected fault at {site} (invocation {fired.count})")
    if fired.kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    return fired
