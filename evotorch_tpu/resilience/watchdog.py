"""First-device-use watchdog: a dead accelerator tunnel should be an
error, not an infinite hang.

On this stack the PJRT plugin pins the platform at interpreter startup;
when the TPU tunnel is unhealthy, the FIRST backend use (``jax.devices()``
or the first dispatch) blocks forever — CLAUDE.md's documented failure
mode, until now survivable only by shell-level timeouts. The probe runs
that first use on a daemon thread with a deadline and turns the hang into
an actionable :class:`DeviceProbeTimeout`.

The probe also catches the plugin's OTHER documented failure: a *silent
CPU fallback* where ``jax.devices()`` returns promptly but with
``CpuDevice`` rows — pass ``expect_accelerator=True`` to make that an
error too (scripts that would otherwise false-fire a TPU battery onto the
CPU).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

__all__ = ["DeviceProbeTimeout", "probe_devices"]

_ENV_TIMEOUT = "EVOTORCH_DEVICE_TIMEOUT"


class DeviceProbeTimeout(RuntimeError):
    """First device use did not complete within the deadline."""


def probe_devices(
    timeout: Optional[float] = None, *, expect_accelerator: bool = False
) -> List:
    """Force the first backend use under a deadline; return the devices.

    ``timeout`` defaults to ``EVOTORCH_DEVICE_TIMEOUT`` (seconds), else 60.
    On timeout the probe thread is left parked (daemonic — it cannot be
    cancelled, which is exactly why the hang must be detected here and not
    discovered at the first rollout) and :class:`DeviceProbeTimeout`
    explains how to force the CPU backend instead.
    """
    if timeout is None:
        timeout = float(os.environ.get(_ENV_TIMEOUT, "60"))
    result: dict = {}

    def _probe():
        try:
            import jax

            result["devices"] = jax.devices()
        except BaseException as exc:  # surfaced on the caller thread below  # graftlint: allow(swallow): handed to the caller thread via the result dict and re-raised there
            result["error"] = exc

    t = threading.Thread(target=_probe, name="device-probe", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        from ..observability.registry import counters

        counters.increment("watchdog.device_probe.timeouts")
        raise DeviceProbeTimeout(
            f"first device use still hanging after {timeout:g}s — the "
            "accelerator tunnel is likely down. Force the CPU backend "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=8 and "
            "jax.config.update('jax_platforms', 'cpu') BEFORE first device "
            "use) or fix the tunnel and retry."
        )
    if "error" in result:
        raise result["error"]
    devices = result["devices"]
    if expect_accelerator and devices and devices[0].platform == "cpu":
        raise DeviceProbeTimeout(
            "device probe returned CPU devices but an accelerator was "
            "required — the PJRT plugin silently fell back to the host "
            "(known failure mode; see CLAUDE.md). Refusing to run an "
            "accelerator workload on the CPU."
        )
    return devices
