"""Fault tolerance for long runs (ISSUE 17; docs/resilience.md).

Three legs, spanning the host runtime, the compiled eval programs, and
the ops tooling:

- :mod:`~evotorch_tpu.resilience.runstate` — durable, self-verifying run
  checkpoint bundles with atomic writes, keep-last-K retention and
  corrupt-bundle fallback; resume is bit-identical because the search
  state is a pure pytree.
- non-finite **score quarantine** lives inside the eval engines
  (``net/vecrl.py:_quarantine_nonfinite``; ``VecNE(nonfinite_quarantine=
  True)`` is the default) — it is listed here because this package's docs
  and tests own its contract: one diverged rollout must not NaN-poison
  ranking, and quarantined counts surface per group in the telemetry
  matrix plus the ``max_nonfinite_share`` SLO rule.
- :mod:`~evotorch_tpu.resilience.retry` /
  :mod:`~evotorch_tpu.resilience.watchdog` /
  :mod:`~evotorch_tpu.resilience.faults` — bounded-backoff retries around
  the fragile host edges, a first-device-use watchdog that converts the
  dead-tunnel hang into an actionable error, and the deterministic
  ``EVOTORCH_FAULTS`` injection harness that keeps every recovery path
  exercised by tests.
"""

from .faults import FaultRule, InjectedFault, configure, fault_point, parse_spec
from .retry import retry_call, retryable
from .runstate import BUNDLE_SCHEMA_VERSION, CorruptBundleError, RunCheckpointer
from .watchdog import DeviceProbeTimeout, probe_devices

__all__ = [
    "FaultRule",
    "InjectedFault",
    "configure",
    "fault_point",
    "parse_spec",
    "retry_call",
    "retryable",
    "BUNDLE_SCHEMA_VERSION",
    "CorruptBundleError",
    "RunCheckpointer",
    "DeviceProbeTimeout",
    "probe_devices",
]
