"""Population-based searchers: GeneticAlgorithm (NSGA-II-like in MOO),
SteadyStateGA, Cosyne.

Parity: reference ``algorithms/ga.py`` — ``ExtendedPopulationMixin``
(``ga.py:62-263``), ``GeneticAlgorithm`` (``ga.py:266-688``),
``SteadyStateGA`` (``ga.py:691-890``), ``Cosyne`` (``ga.py:893-1033``).
"""

from __future__ import annotations

from typing import Iterable, Optional, Union

from ..core import Problem, SolutionBatch
from ..operators.base import CrossOver
from ..operators.real import (
    CosynePermutation,
    GaussianMutation,
    OnePointCrossOver,
    SimulatedBinaryCrossOver,
)
from .searchalgorithm import SearchAlgorithm, SinglePopulationAlgorithmMixin

__all__ = ["ExtendedPopulationMixin", "GeneticAlgorithm", "SteadyStateGA", "Cosyne"]


def _use_operators(population: SolutionBatch, operators: Iterable) -> SolutionBatch:
    """Apply an operator pipeline to produce children (reference ``ga.py:56``)."""
    result = population
    for op in operators:
        result = op(result)
    return result


class ExtendedPopulationMixin:
    """Provides ``_make_extended_population`` with the reference's
    re-evaluation policies (reference ``ga.py:62-263``)."""

    def __init__(
        self,
        *,
        re_evaluate: bool,
        re_evaluate_parents_first: Optional[bool] = None,
        operators: Optional[Iterable] = None,
        allow_empty_operators_list: bool = False,
    ):
        self._operators = [] if operators is None else list(operators)
        if (not allow_empty_operators_list) and len(self._operators) == 0:
            raise ValueError("Please provide at least one operator")
        self._using_cross_over = any(isinstance(op, CrossOver) for op in self._operators)
        self._re_evaluate = bool(re_evaluate)
        if re_evaluate_parents_first is None:
            self._re_evaluate_parents_first = self._using_cross_over
        else:
            if not self._re_evaluate:
                raise ValueError(
                    "re_evaluate_parents_first is only valid when re_evaluate=True"
                )
            self._re_evaluate_parents_first = bool(re_evaluate_parents_first)
        self._first_iter = True

    def _make_extended_population(self, split: bool = False) -> Union[SolutionBatch, tuple]:
        problem: Problem = self.problem
        population: SolutionBatch = self.population

        if self._re_evaluate:
            self._first_iter = False
            if self._re_evaluate_parents_first:
                problem.evaluate(population)
                children = _use_operators(population, self._operators)
                problem.evaluate(children)
                if split:
                    return population, children
                return SolutionBatch.cat([population, children])
            children = _use_operators(population, self._operators)
            extended = SolutionBatch.cat([population, children])
            problem.evaluate(extended)
            if split:
                num_parents = len(population)
                return extended[:num_parents], extended[num_parents:]
            return extended

        if self._first_iter:
            self._first_iter = False
            problem.evaluate(population)
        children = _use_operators(population, self._operators)
        problem.evaluate(children)
        if split:
            return population, children
        return SolutionBatch.cat([population, children])

    @property
    def re_evaluate(self) -> bool:
        return self._re_evaluate

    @property
    def re_evaluate_parents_first(self) -> Optional[bool]:
        return self._re_evaluate_parents_first if self._re_evaluate else None


class GeneticAlgorithm(SearchAlgorithm, SinglePopulationAlgorithmMixin, ExtendedPopulationMixin):
    """Elitist (default) or non-elitist GA over real/int/object dtypes; in
    multi-objective mode the elitist ``take_best`` performs NSGA-II pareto
    selection (reference ``ga.py:266-688``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        operators: Iterable,
        popsize: int,
        elitist: bool = True,
        re_evaluate: bool = True,
        re_evaluate_parents_first: Optional[bool] = None,
        _allow_empty_operator_list: bool = False,
    ):
        SearchAlgorithm.__init__(self, problem)
        self._popsize = int(popsize)
        self._elitist = bool(elitist)
        self._population = problem.generate_batch(self._popsize)
        ExtendedPopulationMixin.__init__(
            self,
            re_evaluate=re_evaluate,
            re_evaluate_parents_first=re_evaluate_parents_first,
            operators=operators,
            allow_empty_operators_list=_allow_empty_operator_list,
        )
        SinglePopulationAlgorithmMixin.__init__(self)

    @property
    def population(self) -> SolutionBatch:
        return self._population

    def _step(self):
        popsize = self._popsize
        if self._elitist:
            extended = self._make_extended_population(split=False)
            self._population = extended.take_best(popsize)
        else:
            parents, children = self._make_extended_population(split=True)
            num_children = len(children)
            if num_children < popsize:
                chosen_parents = self._population.take_best(popsize - num_children)
                self._population = SolutionBatch.cat([chosen_parents, children])
            elif num_children == popsize:
                self._population = children
            else:
                self._population = children.take_best(popsize)


class SteadyStateGA(GeneticAlgorithm):
    """Back-compat wrapper adding ``use(operator)``
    (reference ``ga.py:691-890``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        popsize: int,
        operators: Optional[Iterable] = None,
        elitist: bool = True,
        re_evaluate: bool = True,
        re_evaluate_parents_first: Optional[bool] = None,
    ):
        super().__init__(
            problem,
            operators=operators if operators is not None else [],
            popsize=popsize,
            elitist=elitist,
            re_evaluate=re_evaluate,
            re_evaluate_parents_first=re_evaluate_parents_first,
            _allow_empty_operator_list=True,
        )

    def use(self, operator):
        """Register a cross-over or mutation operator (reference ``ga.py:800``)."""
        self._operators.append(operator)
        self._using_cross_over = self._using_cross_over or isinstance(operator, CrossOver)
        if self._re_evaluate and isinstance(operator, CrossOver):
            self._re_evaluate_parents_first = True

    def _step(self):
        if len(self._operators) == 0:
            raise RuntimeError(
                "SteadyStateGA has no operators; register at least one via use(...)"
            )
        super()._step()


class Cosyne(SearchAlgorithm, SinglePopulationAlgorithmMixin):
    """CoSyNE: cooperative synapse coevolution (Gomez et al. 2008;
    reference ``ga.py:893-1033``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        popsize: int,
        tournament_size: int,
        mutation_stdev: Optional[float],
        mutation_probability: Optional[float] = None,
        permute_all: bool = False,
        num_elites: Optional[int] = None,
        elitism_ratio: Optional[float] = None,
        eta: Optional[float] = None,
        num_children: Optional[int] = None,
    ):
        problem.ensure_numeric()
        SearchAlgorithm.__init__(self, problem)

        if mutation_stdev is None:
            if mutation_probability is not None:
                raise ValueError(
                    "mutation_probability requires mutation_stdev to be given as well"
                )
            self.mutation_op = None
        else:
            self.mutation_op = GaussianMutation(
                problem, stdev=mutation_stdev, mutation_probability=mutation_probability
            )

        cross_over_kwargs = {"tournament_size": int(tournament_size)}
        if num_children is None:
            cross_over_kwargs["cross_over_rate"] = 2.0
        else:
            cross_over_kwargs["num_children"] = int(num_children)
        if eta is None:
            self._cross_over_op = OnePointCrossOver(problem, **cross_over_kwargs)
        else:
            self._cross_over_op = SimulatedBinaryCrossOver(problem, eta=float(eta), **cross_over_kwargs)

        self._permutation_op = CosynePermutation(problem, permute_all=permute_all)

        self._popsize = int(popsize)
        if num_elites is not None and elitism_ratio is None:
            self._num_elites: Optional[int] = int(num_elites)
        elif num_elites is None and elitism_ratio is not None:
            self._num_elites = int(self._popsize * float(elitism_ratio))
        elif num_elites is None and elitism_ratio is None:
            self._num_elites = None
        else:
            raise ValueError("Provide only one of num_elites / elitism_ratio")

        self._population = SolutionBatch(problem, popsize=self._popsize)
        self._first_generation = True
        SinglePopulationAlgorithmMixin.__init__(self)

    @property
    def population(self) -> SolutionBatch:
        return self._population

    def _step(self):
        if self._first_generation:
            self._first_generation = False
            self._problem.evaluate(self._population)

        to_merge = []
        num_elites = self._num_elites
        num_parents = int(self._popsize / 4)
        num_relevant = max((0 if num_elites is None else num_elites), num_parents)
        sorted_relevant = self._population.take_best(num_relevant)
        if num_elites is not None and num_elites >= 1:
            to_merge.append(sorted_relevant[:num_elites].clone())
        parents = sorted_relevant[:num_parents]
        children = self._cross_over_op(parents)
        if self.mutation_op is not None:
            children = self.mutation_op(children)
        permuted = self._permutation_op(self._population)
        to_merge.extend([children, permuted])
        extended = SolutionBatch(merging_of=to_merge)
        self._problem.evaluate(extended)
        self._population = extended.take_best(self._popsize)
