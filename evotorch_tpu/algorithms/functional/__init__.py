"""Pure-functional ask/tell algorithms and optimizers.

Parity: reference ``algorithms/functional/__init__.py`` — ``cem``/``pgpe``
searches and ``adam``/``clipup``/``sgd`` optimizers, all pytree-state based
and batchable (extra leftmost dims on states/hyperparams = batched searches).
"""

from .funcadam import AdamState, adam, adam_ask, adam_tell
from .funcclipup import ClipUpState, clipup, clipup_ask, clipup_tell
from .funccem import CEMState, cem, cem_ask, cem_tell
from .funcga import GAState, default_variation, ga, ga_ask, ga_tell
from .funccmaes import CMAESState, cmaes, cmaes_ask, cmaes_tell
from .funcmapelites import MAPElitesState, mapelites, mapelites_ask, mapelites_tell
from .funcpgpe import (
    PGPEState,
    pgpe,
    pgpe_ask,
    pgpe_ask_lowrank,
    pgpe_ask_trunk_delta,
    pgpe_health,
    pgpe_tell,
    pgpe_tell_lowrank,
    pgpe_tell_trunk_delta,
)
from .funcsnes import SNESState, snes, snes_ask, snes_tell
from .span import make_search_span
from .funcxnes import XNESState, xnes, xnes_ask, xnes_tell
from .funcsgd import SGDState, sgd, sgd_ask, sgd_tell
from .misc import OptimizerFunctions, get_functional_optimizer

__all__ = [
    "AdamState",
    "adam",
    "adam_ask",
    "adam_tell",
    "ClipUpState",
    "clipup",
    "clipup_ask",
    "clipup_tell",
    "CEMState",
    "cem",
    "cem_ask",
    "cem_tell",
    "GAState",
    "ga",
    "ga_ask",
    "ga_tell",
    "default_variation",
    "CMAESState",
    "cmaes",
    "cmaes_ask",
    "cmaes_tell",
    "MAPElitesState",
    "mapelites",
    "mapelites_ask",
    "mapelites_tell",
    "PGPEState",
    "pgpe",
    "pgpe_ask",
    "pgpe_tell",
    "pgpe_ask_lowrank",
    "pgpe_tell_lowrank",
    "pgpe_ask_trunk_delta",
    "pgpe_tell_trunk_delta",
    "pgpe_health",
    "make_search_span",
    "SNESState",
    "snes",
    "snes_ask",
    "snes_tell",
    "XNESState",
    "xnes",
    "xnes_ask",
    "xnes_tell",
    "SGDState",
    "sgd",
    "sgd_ask",
    "sgd_tell",
    "OptimizerFunctions",
    "get_functional_optimizer",
]
