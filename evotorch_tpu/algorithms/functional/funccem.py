"""Functional cross-entropy method: ``cem`` / ``cem_ask`` / ``cem_tell``.

Parity: reference ``algorithms/functional/funccem.py:24-289``, with one
JAX-ism: ``cem_ask`` takes an explicit PRNG ``key`` (the reference relies on
torch global RNG). Batch dims on ``center_init`` / hyperparameters batch the
whole search (reference ``algorithms/functional/__init__.py:152-181``).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...decorators import expects_ndim
from ...distributions import SeparableGaussian
from ...tools.misc import modify_vector, stdev_from_radius
from ...tools.pytree import pytree_dataclass, replace, static_field
from ...tools.ranking import rank
from .misc import as_vector_like

__all__ = ["CEMState", "cem", "cem_ask", "cem_tell"]


@pytree_dataclass
class CEMState:
    center: jnp.ndarray
    stdev: jnp.ndarray
    stdev_min: jnp.ndarray
    stdev_max: jnp.ndarray
    stdev_max_change: jnp.ndarray
    parenthood_ratio: float = static_field()
    maximize: bool = static_field()


def cem(
    *,
    center_init,
    parenthood_ratio: float,
    objective_sense: str,
    stdev_init: Optional[Union[float, jnp.ndarray]] = None,
    radius_init: Optional[Union[float, jnp.ndarray]] = None,
    stdev_min: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max_change: Optional[Union[float, jnp.ndarray]] = None,
) -> CEMState:
    """Initial CEM state (reference ``funccem.py:34-192``)."""
    center_init = jnp.asarray(center_init)
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if radius_init is not None:
        stdev_init = stdev_from_radius(float(radius_init), center_init.shape[-1])
    stdev = as_vector_like(stdev_init, center_init, 0.0)
    return CEMState(
        center=center_init,
        stdev=jnp.broadcast_to(stdev, center_init.shape),
        stdev_min=as_vector_like(stdev_min, center_init, 0.0),
        stdev_max=as_vector_like(stdev_max, center_init, float("inf")),
        stdev_max_change=as_vector_like(stdev_max_change, center_init, float("inf")),
        parenthood_ratio=float(parenthood_ratio),
        maximize=(objective_sense == "max"),
    )


def cem_ask(key, state: CEMState, *, popsize: int) -> jnp.ndarray:
    """Sample a population (reference ``funccem.py:235-247``)."""
    return SeparableGaussian.functional_sample(
        int(popsize), {"mu": state.center, "sigma": state.stdev}, key=key
    )


@expects_ndim(1, 1, 1, 1, 1, 2, 1, None, None)
def _cem_tell_core(
    org_center,
    org_stdev,
    stdev_min,
    stdev_max,
    stdev_max_change,
    values,
    evals,
    parenthood_ratio,
    maximize,
):
    weights = rank(evals, "raw", higher_is_better=maximize)
    grads = SeparableGaussian._compute_gradients_via_parenthood_ratio(
        {"mu": org_center, "sigma": org_stdev, "parenthood_ratio": parenthood_ratio},
        values,
        weights,
    )
    center = org_center + grads["mu"]
    stdev = modify_vector(
        org_stdev,
        org_stdev + grads["sigma"],
        lb=stdev_min,
        ub=stdev_max,
        max_change=stdev_max_change,
    )
    return center, stdev


def cem_tell(state: CEMState, values, evals) -> CEMState:
    """Elite-based distribution update (reference ``funccem.py:249-289``)."""
    center, stdev = _cem_tell_core(
        state.center,
        state.stdev,
        state.stdev_min,
        state.stdev_max,
        state.stdev_max_change,
        values,
        evals,
        state.parenthood_ratio,
        state.maximize,
    )
    return replace(state, center=center, stdev=stdev)
