"""Functional genetic algorithm: ``ga`` / ``ga_ask`` / ``ga_tell``.

The reference ships GA building blocks functionally
(``operators/functional.py``: tournament, crossover, mutation, ``combine``,
``take_best``) but no assembled ask/tell loop; this module provides one, so a
full (elitist) GA — including NSGA-II-style multi-objective selection —
compiles into a single ``lax.scan``. Single- and multi-objective, with a
user-pluggable variation pipeline.

Usage::

    values = ...                          # (popsize, L) initial population
    state = ga(values_init=values, evals_init=f(values), objective_sense="min")
    def gen(state, key):
        children = ga_ask(key, state)     # children only — parent evals are
        state = ga_tell(state, children, f(children))  # reused, not recomputed
        return state, None
    state, _ = jax.lax.scan(gen, state, jax.random.split(key, n_generations))

The caller evaluates the initial population once before the loop; from then
on each generation costs exactly one ``popsize``-sized evaluation (the OO
``GeneticAlgorithm``'s ``re_evaluate=False`` economy).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ...operators import functional as F
from ...tools.pytree import pytree_dataclass, replace, static_field

__all__ = ["GAState", "ga", "ga_ask", "ga_tell", "default_variation"]


@pytree_dataclass
class GAState:
    values: jnp.ndarray  # (popsize, L) current evaluated population
    evals: jnp.ndarray  # (popsize,) or (popsize, n_obj)
    popsize: int = static_field()
    objective_sense: Union[str, tuple] = static_field()
    elitist: bool = static_field()


def default_variation(
    *,
    tournament_size: int = 4,
    num_points: Optional[int] = None,
    eta: Optional[float] = None,
    mutation_stdev: Optional[float] = 0.1,
    mutation_probability: Optional[float] = None,
) -> Callable:
    """Standard pipeline: tournament parent selection, then k-point crossover
    (``num_points``, default 1) or SBX (``eta``) — mutually exclusive — plus
    optional Gaussian mutation."""
    if num_points is not None and eta is not None:
        raise ValueError(
            "Provide either num_points (k-point crossover) or eta (SBX), not both"
        )
    if num_points is None and eta is None:
        num_points = 1

    def variation(key, values, evals, objective_sense, num_children):
        k1, k2 = jax.random.split(key)
        if eta is not None:
            children = F.simulated_binary_cross_over(
                k1, values, evals, eta=eta,
                tournament_size=tournament_size, num_children=num_children,
                objective_sense=objective_sense,
            )
        else:
            children = F.multi_point_cross_over(
                k1, values, evals, num_points=num_points,
                tournament_size=tournament_size, num_children=num_children,
                objective_sense=objective_sense,
            )
        if mutation_stdev is not None:
            children = F.gaussian_mutation(
                k2, children, stdev=mutation_stdev,
                mutation_probability=mutation_probability,
            )
        return children

    return variation


def ga(
    *,
    values_init: jnp.ndarray,
    evals_init: jnp.ndarray,
    objective_sense: Union[str, Sequence[str]],
    elitist: bool = True,
) -> GAState:
    """Initial GA state from an **evaluated** initial population (evaluate it
    once with your fitness function before calling this)."""
    values_init = jnp.asarray(values_init)
    evals_init = jnp.asarray(evals_init)
    if values_init.ndim != 2:
        raise ValueError(f"values_init must be (popsize, L); got {values_init.shape}")
    if evals_init.shape[0] != values_init.shape[0]:
        raise ValueError(
            f"evals_init has {evals_init.shape[0]} rows for {values_init.shape[0]} solutions"
        )
    sense = objective_sense if isinstance(objective_sense, str) else tuple(objective_sense)
    n_obj = 1 if isinstance(sense, str) else len(sense)
    if n_obj > 1 and (evals_init.ndim != 2 or evals_init.shape[1] != n_obj):
        raise ValueError(
            f"evals_init must be (popsize, {n_obj}) for {n_obj} objectives; got {evals_init.shape}"
        )
    return GAState(
        values=values_init,
        evals=evals_init,
        popsize=int(values_init.shape[0]),
        objective_sense=sense,
        elitist=bool(elitist),
    )


def ga_ask(
    key,
    state: GAState,
    *,
    variation: Optional[Callable] = None,
    num_children: Optional[int] = None,
) -> jnp.ndarray:
    """Produce children from the current (evaluated) population via the
    variation pipeline. Only the children need evaluating — the parents'
    fitnesses are already in the state."""
    variation = variation if variation is not None else default_variation()
    sense = state.objective_sense
    sense_arg = sense if isinstance(sense, str) else list(sense)
    n = int(num_children) if num_children is not None else state.popsize
    if n % 2 != 0:
        raise ValueError(f"num_children must be even, got {n}")
    return variation(key, state.values, state.evals, sense_arg, n)


def ga_tell(state: GAState, child_values, child_evals) -> GAState:
    """Select the next population. Elitist: ``take_best`` over
    parents + children (NSGA-II pareto + crowding for multiple objectives);
    non-elitist: children replace parents (topped up with the best parents
    when there are fewer children than popsize)."""
    child_values = jnp.asarray(child_values)
    child_evals = jnp.asarray(child_evals)
    sense = state.objective_sense
    sense_arg = sense if isinstance(sense, str) else list(sense)
    if state.elitist:
        all_values, all_evals = F.combine(
            (state.values, state.evals), (child_values, child_evals),
            objective_sense=sense_arg,
        )
        best_values, best_evals = F.take_best(
            all_values, all_evals, state.popsize, objective_sense=sense_arg
        )
    elif child_values.shape[0] >= state.popsize:
        best_values, best_evals = F.take_best(
            child_values, child_evals, state.popsize, objective_sense=sense_arg
        )
    else:
        deficit = state.popsize - child_values.shape[0]
        top_values, top_evals = F.take_best(
            state.values, state.evals, deficit, objective_sense=sense_arg
        )
        best_values, best_evals = F.combine(
            (top_values, top_evals), (child_values, child_evals),
            objective_sense=sense_arg,
        )
    return replace(state, values=best_values, evals=best_evals)
