"""Functional CMA-ES: ``cmaes`` / ``cmaes_ask`` / ``cmaes_tell``.

The math follows the reference's vectorized torch CMA-ES
(``algorithms/cmaes.py:90-606``, itself based on pycma r3.2.2): rank-mu +
rank-1 + active CMA (``cmaes.py:519-553``), CSA step-size adaptation with the
``h_sig`` stall (``cmaes.py:492-507``, ``cmaes.py:31-46``), separable
(diagonal) mode, and Cholesky decomposition of C at a limited frequency
(``cmaes.py:555-565``, frequency rule ``cmaes.py:382-385``).

TPU-first design: the state is a pytree dataclass and the whole
ask/tell cycle — including the conditional Cholesky refresh, expressed as a
``lax.cond`` — jits into one XLA program, so CMA-ES runs start-to-finish on
device under ``lax.scan``. This functional CMA-ES is an extension over the
reference's functional API (which offers only cem/pgpe); the OO ``CMAES``
class wraps it.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...tools.pytree import pytree_dataclass, replace, static_field

__all__ = ["CMAESState", "cmaes", "cmaes_ask", "cmaes_tell"]


@pytree_dataclass
class CMAESState:
    # search distribution
    m: jnp.ndarray
    sigma: jnp.ndarray
    C: jnp.ndarray  # (d,) when separable, (d, d) otherwise
    A: jnp.ndarray  # sqrt of C (diagonal vector or Cholesky factor)
    p_sigma: jnp.ndarray
    p_c: jnp.ndarray
    iteration: jnp.ndarray  # int32 generation counter
    # last sampled population in local/shaped coordinates (needed by tell)
    zs: jnp.ndarray
    ys: jnp.ndarray
    # constants (pytree leaves so they ride through jit/scan untouched)
    weights: jnp.ndarray
    mu_eff: jnp.ndarray
    c_m: jnp.ndarray
    c_sigma: jnp.ndarray
    damp_sigma: jnp.ndarray
    c_c: jnp.ndarray
    c_1: jnp.ndarray
    c_mu: jnp.ndarray
    variance_discount_sigma: jnp.ndarray
    variance_discount_c: jnp.ndarray
    unbiased_expectation: jnp.ndarray
    stdev_min: jnp.ndarray
    stdev_max: jnp.ndarray
    # static configuration
    popsize: int = static_field()
    mu: int = static_field()
    separable: bool = static_field()
    active: bool = static_field()
    csa_squared: bool = static_field()
    decompose_C_freq: int = static_field()
    maximize: bool = static_field()


def cmaes(
    *,
    center_init,
    stdev_init: float,
    objective_sense: str,
    popsize: Optional[int] = None,
    c_m: float = 1.0,
    c_sigma: Optional[float] = None,
    c_sigma_ratio: float = 1.0,
    damp_sigma: Optional[float] = None,
    damp_sigma_ratio: float = 1.0,
    c_c: Optional[float] = None,
    c_c_ratio: float = 1.0,
    c_1: Optional[float] = None,
    c_1_ratio: float = 1.0,
    c_mu: Optional[float] = None,
    c_mu_ratio: float = 1.0,
    active: bool = True,
    csa_squared: bool = False,
    stdev_min: Optional[float] = None,
    stdev_max: Optional[float] = None,
    separable: bool = False,
    limit_C_decomposition: bool = True,
) -> CMAESState:
    """Initialize CMA-ES with the pycma rules of thumb
    (reference ``cmaes.py:225-389``)."""
    m = jnp.asarray(center_init)
    if m.ndim != 1:
        raise ValueError(f"center_init must be 1-D, got shape {m.shape}")
    d = m.shape[0]
    dtype = m.dtype
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")

    if not popsize:
        popsize = 4 + int(math.floor(3 * math.log(d)))
    popsize = int(popsize)
    mu = int(math.floor(popsize / 2))

    # raw weights: log((lambda+1)/2) - log(i)
    raw_weights = math.log((popsize + 1) / 2) - jnp.log(jnp.arange(popsize, dtype=dtype) + 1)
    positive_weights = raw_weights[:mu]
    negative_weights = raw_weights[mu:]
    mu_eff = jnp.sum(positive_weights) ** 2 / jnp.sum(positive_weights**2)
    mu_eff_f = float(mu_eff)

    if c_sigma is None:
        c_sigma = (mu_eff_f + 2.0) / (d + mu_eff_f + 3)
    c_sigma = c_sigma_ratio * c_sigma
    if damp_sigma is None:
        damp_sigma = 1 + 2 * max(0.0, math.sqrt(max(0.0, (mu_eff_f - 1) / (d + 1))) - 1) + c_sigma
    damp_sigma = damp_sigma_ratio * damp_sigma
    if c_c is None:
        if separable:
            c_c = (1 + (1 / d) + (mu_eff_f / d)) / (d**0.5 + (1 / d) + 2 * (mu_eff_f / d))
        else:
            c_c = (4 + mu_eff_f / d) / (d + (4 + 2 * mu_eff_f / d))
    c_c = c_c_ratio * c_c
    if c_1 is None:
        if separable:
            c_1 = 1.0 / (d + 2.0 * math.sqrt(d) + mu_eff_f / d)
        else:
            c_1 = min(1, popsize / 6) * 2 / ((d + 1.3) ** 2.0 + mu_eff_f)
    c_1 = c_1_ratio * c_1
    if c_mu is None:
        if separable:
            c_mu = (0.25 + mu_eff_f + (1.0 / mu_eff_f) - 2) / (d + 4 * math.sqrt(d) + (mu_eff_f / 2.0))
        else:
            c_mu = min(1 - c_1, 2 * ((0.25 + mu_eff_f - 2 + (1 / mu_eff_f)) / ((d + 2) ** 2.0 + mu_eff_f)))
    c_mu = c_mu_ratio * c_mu

    variance_discount_sigma = math.sqrt(c_sigma * (2 - c_sigma) * mu_eff_f)
    variance_discount_c = math.sqrt(c_c * (2 - c_c) * mu_eff_f)

    positive_weights = positive_weights / jnp.sum(positive_weights)
    if active:
        mu_eff_neg = jnp.sum(negative_weights) ** 2 / jnp.sum(negative_weights**2)
        alpha_mu = 1 + c_1 / c_mu
        alpha_mu_eff = 1 + 2 * float(mu_eff_neg) / (mu_eff_f + 2)
        alpha_pos_def = (1 - c_mu - c_1) / (d * c_mu)
        alpha = min([alpha_mu, alpha_mu_eff, alpha_pos_def])
        negative_weights = alpha * negative_weights / jnp.sum(jnp.abs(negative_weights))
    else:
        negative_weights = jnp.zeros_like(negative_weights)
    weights = jnp.concatenate([positive_weights, negative_weights])

    unbiased_expectation = math.sqrt(d) * (1 - (1 / (4 * d)) + 1 / (21 * d**2))

    if limit_C_decomposition:
        denom = 10 * d * (c_1 + c_mu)
        denom = denom if abs(denom) > 1e-8 else 1e-8
        decompose_C_freq = max(1, int(math.floor(1 / denom)))
    else:
        decompose_C_freq = 1

    if separable:
        C = jnp.ones(d, dtype=dtype)
        A = jnp.ones(d, dtype=dtype)
    else:
        C = jnp.eye(d, dtype=dtype)
        A = jnp.eye(d, dtype=dtype)

    as_arr = lambda x: jnp.asarray(x, dtype=dtype)  # noqa: E731
    return CMAESState(
        m=m,
        sigma=as_arr(stdev_init),
        C=C,
        A=A,
        p_sigma=jnp.zeros(d, dtype=dtype),
        p_c=jnp.zeros(d, dtype=dtype),
        iteration=jnp.zeros((), dtype=jnp.int32),
        zs=jnp.zeros((popsize, d), dtype=dtype),
        ys=jnp.zeros((popsize, d), dtype=dtype),
        weights=weights,
        mu_eff=as_arr(mu_eff),
        c_m=as_arr(c_m),
        c_sigma=as_arr(c_sigma),
        damp_sigma=as_arr(damp_sigma),
        c_c=as_arr(c_c),
        c_1=as_arr(c_1),
        c_mu=as_arr(c_mu),
        variance_discount_sigma=as_arr(variance_discount_sigma),
        variance_discount_c=as_arr(variance_discount_c),
        unbiased_expectation=as_arr(unbiased_expectation),
        stdev_min=as_arr(0.0 if stdev_min is None else stdev_min),
        stdev_max=as_arr(jnp.inf if stdev_max is None else stdev_max),
        popsize=popsize,
        mu=mu,
        separable=bool(separable),
        active=bool(active),
        csa_squared=bool(csa_squared),
        decompose_C_freq=int(decompose_C_freq),
        maximize=(objective_sense == "max"),
    )


def cmaes_ask(key, state: CMAESState):
    """Sample the population: returns ``(new_state, xs)`` where the state
    retains the local (``zs``) and shaped (``ys``) coordinates for the tell
    step (reference ``sample_distribution``, ``cmaes.py:408-430``)."""
    d = state.m.shape[0]
    zs = jax.random.normal(key, (state.popsize, d), dtype=state.m.dtype)
    if state.separable:
        ys = state.A[None, :] * zs
    else:
        ys = zs @ state.A.T
    xs = state.m[None, :] + state.sigma * ys
    return replace(state, zs=zs, ys=ys), xs


def _h_sig(p_sigma, c_sigma, iteration):
    """Stall flag for the rank-1 path (reference ``cmaes.py:31-46``)."""
    d = p_sigma.shape[-1]
    squared_sum = jnp.sum(p_sigma**2) / (1 - (1 - c_sigma) ** (2 * iteration.astype(p_sigma.dtype) + 1))
    stall = (squared_sum / d) - 1 < 1 + 4.0 / (d + 1)
    return stall.astype(p_sigma.dtype)


def _limit_stdev(sigma, C, stdev_min, stdev_max, separable: bool):
    """Clamp the element-wise stdev of sigma^2 C (reference ``cmaes.py:49-80``)."""
    diag = C if separable else jnp.diagonal(C)
    stdevs = sigma * jnp.sqrt(diag)
    stdevs = jnp.clip(stdevs, stdev_min, stdev_max)
    unscaled = (stdevs / sigma) ** 2
    if separable:
        return unscaled
    n = C.shape[0]
    return C * (1 - jnp.eye(n, dtype=C.dtype)) + jnp.diag(unscaled)


def cmaes_tell(state: CMAESState, xs, fitnesses) -> CMAESState:
    """Full CMA-ES update from the evaluated population
    (reference ``_step``, ``cmaes.py:567-606``)."""
    fitnesses = jnp.asarray(fitnesses)
    d = state.m.shape[0]

    # --- rank-based weight assignment (reference cmaes.py:432-453)
    utilities = fitnesses if state.maximize else -fitnesses
    indices = jnp.argsort(-utilities)
    ranks = jnp.zeros_like(indices).at[indices].set(jnp.arange(state.popsize))
    assigned_weights = state.weights[ranks]

    zs, ys = state.zs, state.ys

    # --- center adaptation (reference cmaes.py:455-483)
    top_w, top_idx = jax.lax.top_k(assigned_weights, state.mu)
    local_disp = jnp.sum(top_w[:, None] * zs[top_idx], axis=0)
    shaped_disp = jnp.sum(top_w[:, None] * ys[top_idx], axis=0)
    m = state.m + state.c_m * state.sigma * shaped_disp

    # --- step-size adaptation (reference cmaes.py:485-507)
    p_sigma = (1 - state.c_sigma) * state.p_sigma + state.variance_discount_sigma * local_disp
    if state.csa_squared:
        exponential_update = (jnp.sum(p_sigma**2) / d - 1) / 2
    else:
        exponential_update = jnp.linalg.norm(p_sigma) / state.unbiased_expectation - 1
    sigma = state.sigma * jnp.exp((state.c_sigma / state.damp_sigma) * exponential_update)

    h_sig = _h_sig(p_sigma, state.c_sigma, state.iteration)

    # --- covariance adaptation (reference cmaes.py:509-553)
    p_c = (1 - state.c_c) * state.p_c + h_sig * state.variance_discount_c * shaped_disp
    if state.active:
        assigned_weights = jnp.where(
            assigned_weights > 0,
            assigned_weights,
            d * assigned_weights / jnp.maximum(jnp.sum(zs**2, axis=-1), 1e-23),
        )
    c1a = state.c_1 * (1 - (1 - h_sig**2) * state.c_c * (2 - state.c_c))
    weighted_pc = jnp.sqrt(state.c_1 / (c1a + 1e-23))
    if state.separable:
        r1_update = c1a * (p_c**2 - state.C)
        rmu_update = state.c_mu * jnp.sum(
            assigned_weights[:, None] * (ys**2 - state.C[None, :]), axis=0
        )
    else:
        wpc = weighted_pc * p_c
        r1_update = c1a * (jnp.outer(wpc, wpc) - state.C)
        rmu_update = state.c_mu * (
            jnp.einsum("i,ij,ik->jk", assigned_weights, ys, ys)
            - jnp.sum(state.weights) * state.C
        )
    C = state.C + r1_update + rmu_update

    # --- post-step corrections (reference cmaes.py:592-606)
    C = _limit_stdev(sigma, C, state.stdev_min, state.stdev_max, state.separable)

    def decompose(C):
        if state.separable:
            return jnp.sqrt(C)
        return jnp.linalg.cholesky(C)

    A = jax.lax.cond(
        (state.iteration + 1) % state.decompose_C_freq == 0,
        decompose,
        lambda _: state.A,
        C,
    )

    return replace(
        state,
        m=m,
        sigma=sigma,
        C=C,
        A=A,
        p_sigma=p_sigma,
        p_c=p_c,
        iteration=state.iteration + 1,
    )
