"""Functional-optimizer registry (reference ``algorithms/functional/misc.py:26-76``)
and small shared helpers for the functional algorithms."""

from __future__ import annotations

from typing import Iterable, NamedTuple, Union

import jax.numpy as jnp

__all__ = ["OptimizerFunctions", "get_functional_optimizer", "as_vector_like"]


def as_vector_like(x, center: jnp.ndarray, default: float) -> jnp.ndarray:
    """Coerce a scalar/None/vector hyperparameter into a vector matching the
    center's trailing dimension (the reference's ``as_vector_like_center``,
    ``funcpgpe.py:244-258``)."""
    if x is None:
        x = default
    x = jnp.asarray(x, dtype=center.dtype)
    if x.ndim == 0:
        return jnp.broadcast_to(x, center.shape[-1:])
    return x


class OptimizerFunctions(NamedTuple):
    initialize: callable
    ask: callable
    tell: callable


def get_functional_optimizer(optimizer: Union[str, tuple]) -> OptimizerFunctions:
    """``"adam"`` -> ``(adam, adam_ask, adam_tell)`` etc.; a 3-tuple of
    callables passes through as a custom optimizer."""
    from .funcadam import adam, adam_ask, adam_tell
    from .funcclipup import clipup, clipup_ask, clipup_tell
    from .funcsgd import sgd, sgd_ask, sgd_tell

    if optimizer == "adam":
        return OptimizerFunctions(adam, adam_ask, adam_tell)
    if optimizer == "clipup":
        return OptimizerFunctions(clipup, clipup_ask, clipup_tell)
    if optimizer in ("sgd", "sga", "momentum"):
        return OptimizerFunctions(sgd, sgd_ask, sgd_tell)
    if isinstance(optimizer, str):
        raise ValueError(f"Unrecognized functional optimizer name: {optimizer}")
    if isinstance(optimizer, Iterable):
        a, b, c = optimizer
        return OptimizerFunctions(a, b, c)
    raise TypeError(f"Unrecognized optimizer specification: {optimizer!r}")
