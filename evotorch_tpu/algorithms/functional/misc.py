"""Functional-optimizer registry (reference ``algorithms/functional/misc.py:26-76``)."""

from __future__ import annotations

from typing import Iterable, NamedTuple, Union

__all__ = ["OptimizerFunctions", "get_functional_optimizer"]


class OptimizerFunctions(NamedTuple):
    initialize: callable
    ask: callable
    tell: callable


def get_functional_optimizer(optimizer: Union[str, tuple]) -> OptimizerFunctions:
    """``"adam"`` -> ``(adam, adam_ask, adam_tell)`` etc.; a 3-tuple of
    callables passes through as a custom optimizer."""
    from .funcadam import adam, adam_ask, adam_tell
    from .funcclipup import clipup, clipup_ask, clipup_tell
    from .funcsgd import sgd, sgd_ask, sgd_tell

    if optimizer == "adam":
        return OptimizerFunctions(adam, adam_ask, adam_tell)
    if optimizer == "clipup":
        return OptimizerFunctions(clipup, clipup_ask, clipup_tell)
    if optimizer in ("sgd", "sga", "momentum"):
        return OptimizerFunctions(sgd, sgd_ask, sgd_tell)
    if isinstance(optimizer, str):
        raise ValueError(f"Unrecognized functional optimizer name: {optimizer}")
    if isinstance(optimizer, Iterable):
        a, b, c = optimizer
        return OptimizerFunctions(a, b, c)
    raise TypeError(f"Unrecognized optimizer specification: {optimizer!r}")
