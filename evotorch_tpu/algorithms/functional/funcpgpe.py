"""Functional PGPE: ``pgpe`` / ``pgpe_ask`` / ``pgpe_tell``.

Parity: reference ``algorithms/functional/funcpgpe.py:29-384``: symmetric
(antithetic) sampling by default, 0-centered ranking, a composed functional
optimizer (ClipUp by default) for the center, and a controlled stdev update
(``stdev_max_change``). JAX-ism: ``pgpe_ask`` takes an explicit PRNG key.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...distributions import (
    SeparableGaussian,
    SymmetricSeparableGaussian,
    make_functional_grad_estimator,
)
from ...tools.misc import modify_vector, stdev_from_radius
from ...tools.pytree import pytree_dataclass, replace, static_field
from .misc import as_vector_like, get_functional_optimizer

__all__ = [
    "PGPEState",
    "pgpe",
    "pgpe_ask",
    "pgpe_tell",
    "pgpe_ask_lowrank",
    "pgpe_tell_lowrank",
    "pgpe_ask_trunk_delta",
    "pgpe_tell_trunk_delta",
    "pgpe_health",
]


@pytree_dataclass
class PGPEState:
    optimizer_state: tuple
    stdev: jnp.ndarray
    stdev_learning_rate: jnp.ndarray
    stdev_min: jnp.ndarray
    stdev_max: jnp.ndarray
    stdev_max_change: jnp.ndarray
    optimizer: Union[str, tuple] = static_field()
    ranking_method: str = static_field()
    maximize: bool = static_field()
    symmetric: bool = static_field()


def _dist_class(symmetric: bool):
    return SymmetricSeparableGaussian if symmetric else SeparableGaussian


def _grad_divisors(symmetric: bool) -> dict:
    denominator = "num_directions" if symmetric else "num_solutions"
    return {"divide_mu_grad_by": denominator, "divide_sigma_grad_by": denominator}


def pgpe(
    *,
    center_init,
    center_learning_rate,
    stdev_learning_rate,
    objective_sense: str,
    ranking_method: str = "centered",
    optimizer: Union[str, tuple] = "clipup",
    optimizer_config: Optional[dict] = None,
    stdev_init: Optional[Union[float, jnp.ndarray]] = None,
    radius_init: Optional[Union[float, jnp.ndarray]] = None,
    stdev_min: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max_change: Optional[Union[float, jnp.ndarray]] = 0.2,
    symmetric: bool = True,
) -> PGPEState:
    """Initial PGPE state (reference ``funcpgpe.py:67-301``)."""
    center_init = jnp.asarray(center_init)
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if radius_init is not None:
        stdev_init = stdev_from_radius(float(radius_init), center_init.shape[-1])
    stdev = jnp.broadcast_to(as_vector_like(stdev_init, center_init, 0.0), center_init.shape)

    opt_init, _, _ = get_functional_optimizer(optimizer)
    optimizer_state = opt_init(
        center_init=center_init,
        center_learning_rate=center_learning_rate,
        **(optimizer_config or {}),
    )

    return PGPEState(
        optimizer_state=optimizer_state,
        stdev=stdev,
        stdev_learning_rate=jnp.asarray(stdev_learning_rate, dtype=center_init.dtype),
        stdev_min=as_vector_like(stdev_min, center_init, 0.0),
        stdev_max=as_vector_like(stdev_max, center_init, float("inf")),
        stdev_max_change=as_vector_like(stdev_max_change, center_init, float("inf")),
        optimizer=optimizer,
        ranking_method=str(ranking_method),
        maximize=(objective_sense == "max"),
        symmetric=bool(symmetric),
    )


def pgpe_ask(key, state: PGPEState, *, popsize: int) -> jnp.ndarray:
    """Sample a population around the optimizer's current center
    (reference ``funcpgpe.py:303-320``)."""
    _, opt_ask, _ = get_functional_optimizer(state.optimizer)
    center = opt_ask(state.optimizer_state)
    return _dist_class(state.symmetric).functional_sample(
        int(popsize), {"mu": center, "sigma": state.stdev}, key=key
    )


def pgpe_tell(state: PGPEState, values, evals) -> PGPEState:
    """Estimate gradients from the evaluated population and update both the
    optimizer (center) and the controlled stdev (reference
    ``funcpgpe.py:333-384``)."""
    _, opt_ask, opt_tell = get_functional_optimizer(state.optimizer)
    dist = _dist_class(state.symmetric)
    grad_fn = make_functional_grad_estimator(
        dist,
        objective_sense=("max" if state.maximize else "min"),
        ranking_method=state.ranking_method,
    )
    grads = grad_fn(
        values,
        evals,
        {
            "mu": opt_ask(state.optimizer_state),
            "sigma": state.stdev,
            **_grad_divisors(state.symmetric),
        },
    )
    new_optimizer_state = opt_tell(state.optimizer_state, follow_grad=grads["mu"])
    target_stdev = state.stdev + state.stdev_learning_rate[..., None] * grads["sigma"]
    new_stdev = modify_vector(
        state.stdev,
        target_stdev,
        lb=state.stdev_min,
        ub=state.stdev_max,
        max_change=state.stdev_max_change,
    )
    return replace(state, optimizer_state=new_optimizer_state, stdev=new_stdev)


def pgpe_health(state: PGPEState) -> dict:
    """Algorithm-health scalars for the search-health plane
    (docs/observability.md "Search health").

    Pure and jit-safe: returns DEVICE scalars (``stdev_norm`` always;
    ``velocity_norm`` when the optimizer state carries a velocity, i.e.
    ClipUp or momentum SGD), so callers can compute them inside a compiled
    generation step and apply the usual lag-by-one host read."""
    out = {"stdev_norm": jnp.linalg.norm(state.stdev)}
    velocity = getattr(state.optimizer_state, "velocity", None)
    if velocity is not None:
        out["velocity_norm"] = jnp.linalg.norm(velocity)
    return out


def pgpe_ask_trunk_delta(key, state: PGPEState, *, popsize: int, rank: int, policy):
    """Sample a shared-trunk + per-lane low-rank-delta population around the
    current center (docs/policies.md).

    ``policy`` is the ``FlatParamsPolicy`` being evolved — the delta factors
    are structured per parameter leaf (rank-1 per 2-D weight block), so the
    sampler needs the policy's parameter tree. Returns a
    ``TrunkDeltaParamsBatch`` the vectorized rollout engine evaluates with
    ONE shared-trunk GEMM per layer; the PGPE update is
    :func:`pgpe_tell_trunk_delta` (same factored gradients as low-rank mode,
    through the materialized effective basis)."""
    import jax

    if not state.symmetric:
        raise ValueError(
            "pgpe_ask_trunk_delta requires symmetric=True (the PGPE default)"
        )
    # lazy import: algorithms (L2) must not import neuroevolution (L3) at
    # module scope
    from ...neuroevolution.net.lowrank import sample_trunk_delta_factors

    _, opt_ask, _ = get_functional_optimizer(state.optimizer)
    center = opt_ask(state.optimizer_state)
    key_factors, key_coeffs = jax.random.split(key)
    factors, basis = sample_trunk_delta_factors(
        key_factors, policy, state.stdev, int(rank)
    )
    return SymmetricSeparableGaussian._sample_trunk_delta(
        key_coeffs,
        {"mu": center, "sigma": state.stdev},
        int(popsize),
        int(rank),
        factors,
        basis,
    )


# ----------------------- low-rank perturbation mode -------------------------
# The MXU path for wide policies (VERDICT r2 #2): the population is
# theta_i = c + (sigma * B) z_i with a shared per-generation basis B and
# per-lane coefficients z_i. The sampling and factored-gradient math live on
# SymmetricSeparableGaussian (distributions.py) so the OO API shares ONE
# implementation with this functional form; see the commentary there for the
# variance-calibration caveat at small rank.


def pgpe_ask_lowrank(key, state: PGPEState, *, popsize: int, rank: int):
    """Sample a low-rank-structured population around the current center.

    Returns a ``LowRankParamsBatch`` the vectorized rollout engine accepts in
    place of a dense ``(popsize, L)`` matrix. Requires symmetric mode (the
    PGPE default) and an even ``popsize``."""
    if not state.symmetric:
        raise ValueError("pgpe_ask_lowrank requires symmetric=True (the PGPE default)")
    _, opt_ask, _ = get_functional_optimizer(state.optimizer)
    center = opt_ask(state.optimizer_state)
    return SymmetricSeparableGaussian._sample_lowrank(
        key, {"mu": center, "sigma": state.stdev}, int(popsize), int(rank)
    )


def pgpe_tell_lowrank(state: PGPEState, params, evals) -> PGPEState:
    """The PGPE update from a factored-evaluated population (low-rank OR
    trunk-delta — the gradients read only the shared effective basis and the
    per-lane coefficients): identical math to ``pgpe_tell`` on the
    materialized population, computed in O(L * rank) without building it."""
    from ...tools.ranking import rank as rank_fn

    if not state.symmetric:
        raise ValueError("pgpe_tell_lowrank requires symmetric=True (the PGPE default)")
    _, opt_ask, opt_tell = get_functional_optimizer(state.optimizer)
    weights = rank_fn(
        jnp.asarray(evals), state.ranking_method, higher_is_better=state.maximize
    )
    grads = SymmetricSeparableGaussian._compute_gradients_lowrank(
        {
            "mu": opt_ask(state.optimizer_state),
            "sigma": state.stdev,
            **_grad_divisors(True),
        },
        params,
        weights,
        state.ranking_method,
    )
    new_optimizer_state = opt_tell(state.optimizer_state, follow_grad=grads["mu"])
    target_stdev = state.stdev + state.stdev_learning_rate[..., None] * grads["sigma"]
    new_stdev = modify_vector(
        state.stdev,
        target_stdev,
        lb=state.stdev_min,
        ub=state.stdev_max,
        max_change=state.stdev_max_change,
    )
    return replace(state, optimizer_state=new_optimizer_state, stdev=new_stdev)


#: the trunk-delta batch carries its materialized effective basis, so the
#: factored update applies verbatim
pgpe_tell_trunk_delta = pgpe_tell_lowrank
