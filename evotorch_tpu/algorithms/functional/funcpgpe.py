"""Functional PGPE: ``pgpe`` / ``pgpe_ask`` / ``pgpe_tell``.

Parity: reference ``algorithms/functional/funcpgpe.py:29-384``: symmetric
(antithetic) sampling by default, 0-centered ranking, a composed functional
optimizer (ClipUp by default) for the center, and a controlled stdev update
(``stdev_max_change``). JAX-ism: ``pgpe_ask`` takes an explicit PRNG key.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...distributions import (
    SeparableGaussian,
    SymmetricSeparableGaussian,
    make_functional_grad_estimator,
)
from ...tools.misc import modify_vector, stdev_from_radius
from ...tools.pytree import pytree_dataclass, replace, static_field
from .misc import as_vector_like, get_functional_optimizer

__all__ = [
    "PGPEState",
    "pgpe",
    "pgpe_ask",
    "pgpe_tell",
    "pgpe_ask_lowrank",
    "pgpe_tell_lowrank",
]


@pytree_dataclass
class PGPEState:
    optimizer_state: tuple
    stdev: jnp.ndarray
    stdev_learning_rate: jnp.ndarray
    stdev_min: jnp.ndarray
    stdev_max: jnp.ndarray
    stdev_max_change: jnp.ndarray
    optimizer: Union[str, tuple] = static_field()
    ranking_method: str = static_field()
    maximize: bool = static_field()
    symmetric: bool = static_field()


def _dist_class(symmetric: bool):
    return SymmetricSeparableGaussian if symmetric else SeparableGaussian


def _grad_divisors(symmetric: bool) -> dict:
    denominator = "num_directions" if symmetric else "num_solutions"
    return {"divide_mu_grad_by": denominator, "divide_sigma_grad_by": denominator}


def pgpe(
    *,
    center_init,
    center_learning_rate,
    stdev_learning_rate,
    objective_sense: str,
    ranking_method: str = "centered",
    optimizer: Union[str, tuple] = "clipup",
    optimizer_config: Optional[dict] = None,
    stdev_init: Optional[Union[float, jnp.ndarray]] = None,
    radius_init: Optional[Union[float, jnp.ndarray]] = None,
    stdev_min: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max: Optional[Union[float, jnp.ndarray]] = None,
    stdev_max_change: Optional[Union[float, jnp.ndarray]] = 0.2,
    symmetric: bool = True,
) -> PGPEState:
    """Initial PGPE state (reference ``funcpgpe.py:67-301``)."""
    center_init = jnp.asarray(center_init)
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if radius_init is not None:
        stdev_init = stdev_from_radius(float(radius_init), center_init.shape[-1])
    stdev = jnp.broadcast_to(as_vector_like(stdev_init, center_init, 0.0), center_init.shape)

    opt_init, _, _ = get_functional_optimizer(optimizer)
    optimizer_state = opt_init(
        center_init=center_init,
        center_learning_rate=center_learning_rate,
        **(optimizer_config or {}),
    )

    return PGPEState(
        optimizer_state=optimizer_state,
        stdev=stdev,
        stdev_learning_rate=jnp.asarray(stdev_learning_rate, dtype=center_init.dtype),
        stdev_min=as_vector_like(stdev_min, center_init, 0.0),
        stdev_max=as_vector_like(stdev_max, center_init, float("inf")),
        stdev_max_change=as_vector_like(stdev_max_change, center_init, float("inf")),
        optimizer=optimizer,
        ranking_method=str(ranking_method),
        maximize=(objective_sense == "max"),
        symmetric=bool(symmetric),
    )


def pgpe_ask(key, state: PGPEState, *, popsize: int) -> jnp.ndarray:
    """Sample a population around the optimizer's current center
    (reference ``funcpgpe.py:303-320``)."""
    _, opt_ask, _ = get_functional_optimizer(state.optimizer)
    center = opt_ask(state.optimizer_state)
    return _dist_class(state.symmetric).functional_sample(
        int(popsize), {"mu": center, "sigma": state.stdev}, key=key
    )


def pgpe_tell(state: PGPEState, values, evals) -> PGPEState:
    """Estimate gradients from the evaluated population and update both the
    optimizer (center) and the controlled stdev (reference
    ``funcpgpe.py:333-384``)."""
    _, opt_ask, opt_tell = get_functional_optimizer(state.optimizer)
    dist = _dist_class(state.symmetric)
    grad_fn = make_functional_grad_estimator(
        dist,
        objective_sense=("max" if state.maximize else "min"),
        ranking_method=state.ranking_method,
    )
    grads = grad_fn(
        values,
        evals,
        {
            "mu": opt_ask(state.optimizer_state),
            "sigma": state.stdev,
            **_grad_divisors(state.symmetric),
        },
    )
    new_optimizer_state = opt_tell(state.optimizer_state, follow_grad=grads["mu"])
    target_stdev = state.stdev + state.stdev_learning_rate[..., None] * grads["sigma"]
    new_stdev = modify_vector(
        state.stdev,
        target_stdev,
        lb=state.stdev_min,
        ub=state.stdev_max,
        max_change=state.stdev_max_change,
    )
    return replace(state, optimizer_state=new_optimizer_state, stdev=new_stdev)


# ----------------------- low-rank perturbation mode -------------------------
# The MXU path for wide policies (net/lowrank.py, VERDICT r2 #2): the
# population is theta_i = c + (sigma * B) z_i with a shared per-generation
# basis B (L, rank) and per-lane coefficients z_i — and both the sampling and
# the PGPE gradient estimate factor through the basis, so the dense (N, L)
# population matrix is never materialized. With B entries ~ N(0, 1/rank) the
# per-coordinate marginal variance of a perturbation is exactly sigma^2, so
# the sigma-adaptation calibration matches the dense symmetric sampler.
#
# No reference counterpart (the reference evaluates dense populations only);
# the math below is the dense SymmetricSeparableGaussian gradient
# (distributions.py:382-401 here, reference distributions.py:616-773)
# rewritten in factored form:
#   mu_grad    = B_eff @ (((f+ - f-)/2) @ Z) / D
#   sigma_grad = ((rowquad(B_eff, Z' diag((f+ + f-)/2) Z) - sum(w) sigma^2)
#                 / sigma) / D
# which equal the dense formulas exactly (tested).


def pgpe_ask_lowrank(key, state: PGPEState, *, popsize: int, rank: int):
    """Sample a low-rank-structured population around the current center.

    Returns a ``LowRankParamsBatch`` the vectorized rollout engine accepts in
    place of a dense ``(popsize, L)`` matrix. Requires symmetric mode (the
    PGPE default) and an even ``popsize``."""
    import jax

    from ...neuroevolution.net.lowrank import LowRankParamsBatch

    if not state.symmetric:
        raise ValueError("pgpe_ask_lowrank requires symmetric=True (the PGPE default)")
    popsize = int(popsize)
    if popsize % 2 != 0:
        raise ValueError(f"popsize must be even for symmetric sampling, got {popsize}")
    _, opt_ask, _ = get_functional_optimizer(state.optimizer)
    center = opt_ask(state.optimizer_state)
    length = center.shape[-1]
    rank = int(rank)
    key_basis, key_coeffs = jax.random.split(key)
    basis = jax.random.normal(key_basis, (length, rank), dtype=center.dtype) / jnp.sqrt(
        jnp.asarray(float(rank), center.dtype)
    )
    basis = state.stdev[:, None] * basis  # sigma folded in: delta = basis @ z
    num_directions = popsize // 2
    z = jax.random.normal(key_coeffs, (num_directions, rank), dtype=center.dtype)
    # interleaved antithetic pairs [+z0, -z0, +z1, -z1, ...] (the dense
    # sampler's direction layout, distributions.py:378-380)
    coeffs = jnp.stack([z, -z], axis=1).reshape(popsize, rank)
    return LowRankParamsBatch(center=center, basis=basis, coeffs=coeffs)


def pgpe_tell_lowrank(state: PGPEState, params, evals) -> PGPEState:
    """The PGPE update from a low-rank-evaluated population: identical math
    to ``pgpe_tell`` on the materialized population, computed in O(L * rank)
    without building it."""
    from ...distributions import _zero_center_weights
    from ...tools.ranking import rank as rank_fn

    _, opt_ask, opt_tell = get_functional_optimizer(state.optimizer)
    weights = rank_fn(
        jnp.asarray(evals), state.ranking_method, higher_is_better=state.maximize
    )
    weights = _zero_center_weights(weights, state.ranking_method)

    z = params.coeffs[0::2]  # (D, rank): the +z of each antithetic pair
    fdplus = weights[0::2]
    fdminus = weights[1::2]
    num_directions = z.shape[0]
    basis = params.basis  # sigma-folded effective basis

    mu_coeff = (fdplus - fdminus) / 2  # (D,)
    mu_grad = (basis @ (mu_coeff @ z)) / num_directions

    w_s = (fdplus + fdminus) / 2
    m = z.T @ (w_s[:, None] * z)  # (rank, rank)
    rowquad = jnp.einsum("lm,mn,ln->l", basis, m, basis)
    sigma = state.stdev
    sigma_grad = ((rowquad - jnp.sum(w_s) * sigma**2) / sigma) / num_directions

    new_optimizer_state = opt_tell(state.optimizer_state, follow_grad=mu_grad)
    target_stdev = state.stdev + state.stdev_learning_rate[..., None] * sigma_grad
    new_stdev = modify_vector(
        state.stdev,
        target_stdev,
        lb=state.stdev_min,
        ub=state.stdev_max,
        max_change=state.stdev_max_change,
    )
    return replace(state, optimizer_state=new_optimizer_state, stdev=new_stdev)
