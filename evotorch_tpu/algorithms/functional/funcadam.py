"""Functional Adam optimizer: ``adam`` / ``adam_ask`` / ``adam_tell``.

Parity: reference ``algorithms/functional/funcadam.py:23-172``. The state is a
pytree dataclass; batch dimensions on ``center_init`` or any hyperparameter
batch the whole optimizer (nested searches), matching the reference's
``expects_ndim`` behavior but via native broadcasting.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...decorators import expects_ndim
from ...tools.pytree import pytree_dataclass, replace

__all__ = ["AdamState", "adam", "adam_ask", "adam_tell"]


@pytree_dataclass
class AdamState:
    center: jnp.ndarray
    center_learning_rate: jnp.ndarray
    beta1: jnp.ndarray
    beta2: jnp.ndarray
    epsilon: jnp.ndarray
    m: jnp.ndarray
    v: jnp.ndarray
    t: jnp.ndarray


def adam(
    *,
    center_init,
    center_learning_rate=0.001,
    beta1=0.9,
    beta2=0.999,
    epsilon=1e-8,
) -> AdamState:
    """Initialize Adam (reference ``funcadam.py:34-104``). Extra leftmost dims
    on any argument are batch dimensions."""
    center_init = jnp.asarray(center_init)
    dtype = center_init.dtype
    as_arr = lambda x: jnp.asarray(x, dtype=dtype)  # noqa: E731
    return AdamState(
        center=center_init,
        center_learning_rate=as_arr(center_learning_rate),
        beta1=as_arr(beta1),
        beta2=as_arr(beta2),
        epsilon=as_arr(epsilon),
        m=jnp.zeros_like(center_init),
        v=jnp.zeros_like(center_init),
        t=jnp.zeros(center_init.shape[:-1], dtype=dtype),
    )


@expects_ndim(1, 1, 0, 0, 0, 0, 1, 1, 0)
def _adam_step(g, center, center_learning_rate, beta1, beta2, epsilon, m, v, t):
    t = t + 1
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g**2
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    center = center + center_learning_rate * mhat / (jnp.sqrt(vhat) + epsilon)
    return center, m, v, t


def adam_ask(state: AdamState) -> jnp.ndarray:
    return state.center


def adam_tell(state: AdamState, *, follow_grad) -> AdamState:
    """Apply an ascent gradient (reference ``funcadam.py:140-172``)."""
    center, m, v, t = _adam_step(
        follow_grad,
        state.center,
        state.center_learning_rate,
        state.beta1,
        state.beta2,
        state.epsilon,
        state.m,
        state.v,
        state.t,
    )
    return replace(state, center=center, m=m, v=v, t=t)
