"""Functional XNES: ``xnes`` / ``xnes_ask`` / ``xnes_tell``.

An extension over the reference's functional API: the ``ExpGaussian``
full-covariance math (reference ``distributions.py:813-1016``) with the OO
defaults of ``gaussian.py:1183-1405``, as an ask/tell pytree state.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ...decorators import expects_ndim
from ...distributions import ExpGaussian
from ...tools.pytree import pytree_dataclass, replace, static_field
from ...tools.ranking import rank

__all__ = ["XNESState", "xnes", "xnes_ask", "xnes_tell"]


@pytree_dataclass
class XNESState:
    center: jnp.ndarray
    A: jnp.ndarray
    A_inv: jnp.ndarray
    center_learning_rate: jnp.ndarray
    stdev_learning_rate: jnp.ndarray
    ranking_method: str = static_field()
    maximize: bool = static_field()


def xnes(
    *,
    center_init,
    objective_sense: str,
    stdev_init: Optional[Union[float, jnp.ndarray]] = None,
    radius_init: Optional[Union[float, jnp.ndarray]] = None,
    center_learning_rate: Optional[float] = None,
    stdev_learning_rate: Optional[float] = None,
    ranking_method: str = "nes",
) -> XNESState:
    center_init = jnp.asarray(center_init)
    n = center_init.shape[-1]
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if radius_init is not None:
        # radius may be batched (one radius per search lane)
        stdev_init = jnp.asarray(radius_init, dtype=center_init.dtype) / jnp.sqrt(
            jnp.asarray(n, dtype=center_init.dtype)
        )
    stdev_init = jnp.asarray(stdev_init, dtype=center_init.dtype)
    # batched center -> batched (eye-scaled) A; stdev may be a scalar, a
    # length-n vector, or a per-lane batch (shape == center batch shape)
    batch_shape = center_init.shape[:-1]
    if stdev_init.ndim > 0 and stdev_init.shape == batch_shape:
        # one stdev per search lane (ambiguous only when num_lanes == n; a
        # per-dimension stdev then needs an explicit trailing axis)
        diag = jnp.broadcast_to(stdev_init[..., None], batch_shape + (n,))
    else:
        diag = jnp.broadcast_to(stdev_init, batch_shape + (n,))
    eye = jnp.eye(n, dtype=center_init.dtype)
    A = eye * diag[..., None, :]
    A_inv = eye * (1.0 / jnp.maximum(diag, 1e-30))[..., None, :]
    if center_learning_rate is None:
        center_learning_rate = 1.0
    if stdev_learning_rate is None:
        stdev_learning_rate = 0.6 * (3 + math.log(n)) / (n * math.sqrt(n))
    return XNESState(
        center=center_init,
        A=A,
        A_inv=A_inv,
        center_learning_rate=jnp.asarray(center_learning_rate, dtype=center_init.dtype),
        stdev_learning_rate=jnp.asarray(stdev_learning_rate, dtype=center_init.dtype),
        ranking_method=str(ranking_method),
        maximize=(objective_sense == "max"),
    )


def xnes_ask(key, state: XNESState, *, popsize: int) -> jnp.ndarray:
    """Batched-state aware: extra leftmost dims on the state's arrays are
    batch dims (independent searches with independent noise)."""
    return ExpGaussian.functional_sample(
        int(popsize),
        {"mu": state.center, "sigma": state.A, "sigma_inv": state.A_inv},
        key=key,
    )


def _make_xnes_tell_core(ranking_method: str, maximize: bool):
    @expects_ndim(1, 2, 2, 0, 0, 2, 1)
    def core(center, A, A_inv, clr, slr, values, evals):
        weights = rank(evals, ranking_method, higher_is_better=maximize)
        grads = ExpGaussian._compute_gradients(
            {"mu": center, "sigma": A, "sigma_inv": A_inv}, values, weights, ranking_method
        )
        update_d = clr * grads["d"]
        update_M = slr * grads["M"]
        expm = jax.scipy.linalg.expm
        new_center = center + A @ update_d
        new_A = A @ expm(0.5 * update_M)
        new_A_inv = expm(-0.5 * update_M) @ A_inv
        return new_center, new_A, new_A_inv

    return core


def xnes_tell(state: XNESState, values, evals) -> XNESState:
    core = _make_xnes_tell_core(state.ranking_method, state.maximize)
    center, A, A_inv = core(
        state.center,
        state.A,
        state.A_inv,
        state.center_learning_rate,
        state.stdev_learning_rate,
        jnp.asarray(values),
        jnp.asarray(evals),
    )
    return replace(state, center=center, A=A, A_inv=A_inv)
