"""Functional MAP-Elites: ``mapelites`` / ``mapelites_ask`` / ``mapelites_tell``.

The OO ``MAPElites`` (``algorithms/mapelites.py``) wraps the Problem
machinery; this is the pure pytree-state form, so a whole
quality-diversity run — archive updates included — compiles into one
``lax.scan``. The per-cell best-solution selection is the same vmapped kernel
(reference ``mapelites.py:24-67``).

Fitness convention: ``evals[:, 0]`` is the fitness, ``evals[:, 1:]`` are the
feature coordinates (the reference's eval-data layout).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from ...tools.pytree import pytree_dataclass, replace, static_field
from ..mapelites import _best_solutions_for_all_cells

__all__ = ["MAPElitesState", "mapelites", "mapelites_ask", "mapelites_tell"]


@pytree_dataclass
class MAPElitesState:
    values: jnp.ndarray  # (num_cells, L) archive decision values
    evals: jnp.ndarray  # (num_cells, 1 + num_features)
    filled: jnp.ndarray  # (num_cells,) bool
    feature_grid: jnp.ndarray  # (num_cells, num_features, 2)
    objective_sense: str = static_field()


def mapelites(
    *,
    values_init: jnp.ndarray,
    evals_init: jnp.ndarray,
    feature_grid,
    objective_sense: str,
) -> MAPElitesState:
    """Initial archive from an **evaluated** seed population (one candidate
    per cell; extra/missing rows are resolved by the first tell)."""
    values_init = jnp.asarray(values_init)
    evals_init = jnp.asarray(evals_init)
    feature_grid = jnp.asarray(feature_grid)
    if values_init.ndim != 2:
        raise ValueError(f"values_init must be (N, L); got {values_init.shape}")
    if evals_init.shape[0] != values_init.shape[0]:
        raise ValueError(
            f"evals_init has {evals_init.shape[0]} rows for {values_init.shape[0]} solutions"
        )
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if feature_grid.ndim != 3 or feature_grid.shape[-1] != 2:
        raise ValueError(
            f"feature_grid must be (num_cells, num_features, 2); got {feature_grid.shape}"
        )
    num_cells = feature_grid.shape[0]
    if evals_init.ndim != 2 or evals_init.shape[1] != 1 + feature_grid.shape[1]:
        raise ValueError(
            f"evals_init must be (N, 1 + num_features) = (N, {1 + feature_grid.shape[1]}); "
            f"got {evals_init.shape}"
        )
    # place the seed population into cells via one selection pass
    values, evals, filled = _best_solutions_for_all_cells(
        objective_sense, values_init, evals_init, feature_grid
    )
    return MAPElitesState(
        values=values,
        evals=evals,
        filled=filled,
        feature_grid=feature_grid,
        objective_sense=objective_sense,
    )


def mapelites_ask(key, state: MAPElitesState, *, mutate: Callable) -> jnp.ndarray:
    """Children: mutate the current archive occupants (one child per cell —
    the vectorized emit step). ``mutate(key, values) -> values``."""
    return mutate(key, state.values)


def mapelites_tell(state: MAPElitesState, child_values, child_evals) -> MAPElitesState:
    """Insert children: for every cell, keep the best candidate (current
    occupant or any child) whose features fall inside the cell bounds."""
    child_values = jnp.asarray(child_values)
    child_evals = jnp.asarray(child_evals)
    if child_evals.shape[0] != child_values.shape[0]:
        raise ValueError(
            f"child_evals has {child_evals.shape[0]} rows for {child_values.shape[0]} children"
        )
    # candidates = current archive + children; unfilled archive rows are
    # masked out by pushing their fitness to the losing extreme
    bad = jnp.inf if state.objective_sense == "min" else -jnp.inf
    arch_fitness = jnp.where(state.filled, state.evals[:, 0], bad)
    arch_evals = state.evals.at[:, 0].set(arch_fitness)
    all_values = jnp.concatenate([state.values, child_values], axis=0)
    all_evals = jnp.concatenate([arch_evals, child_evals], axis=0)
    values, evals, filled = _best_solutions_for_all_cells(
        state.objective_sense, all_values, all_evals, state.feature_grid
    )
    return replace(state, values=values, evals=evals, filled=filled)
