"""Functional ClipUp optimizer: ``clipup`` / ``clipup_ask`` / ``clipup_tell``.

Parity: reference ``algorithms/functional/funcclipup.py:23-151`` (and the
stateful ``optimizers.py:231-418``): normalize the gradient to
``center_learning_rate``, momentum-accumulate the velocity, clip the velocity
norm to ``max_speed`` (default ``2 * center_learning_rate``).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...decorators import expects_ndim
from ...tools.pytree import pytree_dataclass, replace

__all__ = ["ClipUpState", "clipup", "clipup_ask", "clipup_tell"]


@pytree_dataclass
class ClipUpState:
    center: jnp.ndarray
    velocity: jnp.ndarray
    center_learning_rate: jnp.ndarray
    momentum: jnp.ndarray
    max_speed: jnp.ndarray


def clipup(
    *,
    center_init,
    momentum=0.9,
    center_learning_rate: Optional[float] = None,
    max_speed: Optional[float] = None,
) -> ClipUpState:
    """Initialize ClipUp (reference ``funcclipup.py:31-92``). At least one of
    ``center_learning_rate`` / ``max_speed`` is required; the missing one is
    derived via the factor-of-2 rule."""
    center_init = jnp.asarray(center_init)
    dtype = center_init.dtype
    as_arr = lambda x: jnp.asarray(x, dtype=dtype)  # noqa: E731
    if center_learning_rate is None and max_speed is None:
        raise ValueError(
            "Both `center_learning_rate` and `max_speed` are missing. At least one of them is needed."
        )
    if max_speed is None:
        center_learning_rate = as_arr(center_learning_rate)
        max_speed = center_learning_rate * 2.0
    elif center_learning_rate is None:
        max_speed = as_arr(max_speed)
        center_learning_rate = max_speed / 2.0
    else:
        center_learning_rate = as_arr(center_learning_rate)
        max_speed = as_arr(max_speed)
    return ClipUpState(
        center=center_init,
        velocity=jnp.zeros_like(center_init),
        center_learning_rate=center_learning_rate,
        momentum=as_arr(momentum),
        max_speed=max_speed,
    )


@expects_ndim(1, 1, 1, 0, 0, 0)
def _clipup_step(g, center, velocity, center_learning_rate, momentum, max_speed):
    gnorm = jnp.linalg.norm(g)
    velocity = momentum * velocity + center_learning_rate * (g / gnorm)
    vnorm = jnp.linalg.norm(velocity)
    velocity = jnp.where(vnorm > max_speed, max_speed * (velocity / vnorm), velocity)
    center = center + velocity
    return velocity, center


def clipup_ask(state: ClipUpState) -> jnp.ndarray:
    return state.center


def clipup_tell(state: ClipUpState, *, follow_grad) -> ClipUpState:
    """Apply an ascent gradient (reference ``funcclipup.py:119-151``)."""
    velocity, center = _clipup_step(
        follow_grad,
        state.center,
        state.velocity,
        state.center_learning_rate,
        state.momentum,
        state.max_speed,
    )
    return replace(state, center=center, velocity=velocity)
