"""``make_search_span``: K ask->fitness->tell generations scanned into one
jitted, state-donating program.

The functional-searcher counterpart of ``parallel.make_training_span`` for
objectives that are plain jax functions (no rollout engine): the ONE
scanned-generations idiom in the repo — ``examples/functional_batched_search``
and the program-ledger's batched-search gate program are both built on it.
Because every functional searcher state is a pytree and ``ask``/``tell`` are
pure, the helper composes with ``jax.vmap`` for batched searches exactly like
a hand-rolled scan would (evosax-style ES batteries, PAPERS.md).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

__all__ = ["make_search_span"]


def make_search_span(
    fitness: Callable,
    *,
    ask: Callable,
    tell: Callable,
    metrics: Optional[Callable] = None,
    donate_state: bool = True,
):
    """Fuse K generations of a functional searcher into one donated program.

    ``ask(key, state) -> population`` (bind popsize et al. with
    ``functools.partial``), ``fitness(population) -> evals`` and
    ``tell(state, population, evals) -> state`` are scanned ``len(keys)``
    times; ``metrics(population, evals) -> pytree`` (default: the raw evals)
    picks what is stacked per generation as the scan ys.

    Returns ``span_fn(state, keys) -> (state, ys)`` — jitted with the state
    donated (``donate_state=False`` opts out, e.g. when the caller reuses the
    initial state for an A/B). ``keys`` is a ``(K,)`` PRNG key array, one per
    generation; resume-friendly callers derive them from absolute generation
    indices (``jax.random.fold_in``) so a restarted run replays the identical
    stream. Bit-identity with a hand-rolled ``lax.scan`` over the same body
    holds by construction (same trace); K separately-jitted sequential calls
    agree numerically but XLA may reassociate float reductions across the
    per-call program boundaries.
    """

    def generation(state, key):
        population = ask(key, state)
        evals = fitness(population)
        new_state = tell(state, population, evals)
        out = evals if metrics is None else metrics(population, evals)
        return new_state, out

    def span_fn(state, keys):
        return jax.lax.scan(generation, state, keys)

    return jax.jit(span_fn, donate_argnums=(0,) if donate_state else ())
