"""Functional SGD (with optional momentum): ``sgd`` / ``sgd_ask`` / ``sgd_tell``.

Parity: reference ``algorithms/functional/funcsgd.py:23-130``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...decorators import expects_ndim
from ...tools.pytree import pytree_dataclass, replace

__all__ = ["SGDState", "sgd", "sgd_ask", "sgd_tell"]


@pytree_dataclass
class SGDState:
    center: jnp.ndarray
    velocity: jnp.ndarray
    center_learning_rate: jnp.ndarray
    momentum: jnp.ndarray


def sgd(
    *,
    center_init,
    center_learning_rate,
    momentum: Optional[float] = None,
) -> SGDState:
    """Initialize SGD (reference ``funcsgd.py:30-77``). ``momentum=None``
    means plain gradient ascent."""
    center_init = jnp.asarray(center_init)
    dtype = center_init.dtype
    return SGDState(
        center=center_init,
        velocity=jnp.zeros_like(center_init),
        center_learning_rate=jnp.asarray(center_learning_rate, dtype=dtype),
        momentum=jnp.asarray(0.0 if momentum is None else momentum, dtype=dtype),
    )


@expects_ndim(1, 1, 1, 0, 0)
def _sgd_step(g, center, velocity, center_learning_rate, momentum):
    velocity = momentum * velocity + center_learning_rate * g
    center = center + velocity
    return velocity, center


def sgd_ask(state: SGDState) -> jnp.ndarray:
    return state.center


def sgd_tell(state: SGDState, *, follow_grad) -> SGDState:
    velocity, center = _sgd_step(
        follow_grad,
        state.center,
        state.velocity,
        state.center_learning_rate,
        state.momentum,
    )
    return replace(state, center=center, velocity=velocity)
