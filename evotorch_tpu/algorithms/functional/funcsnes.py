"""Functional SNES: ``snes`` / ``snes_ask`` / ``snes_tell``.

An extension over the reference's functional API (which offers only cem/pgpe,
``algorithms/functional/__init__.py``): the same ask/tell pytree-state shape
applied to SNES (Schaul et al. 2011), using the ``ExpSeparableGaussian``
natural-gradient math of ``distributions.py`` (reference
``distributions.py:776-810``) and the OO defaults of ``gaussian.py:746-983``.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ...distributions import ExpSeparableGaussian, make_functional_grad_estimator
from ...tools.pytree import pytree_dataclass, replace, static_field
from .misc import as_vector_like

__all__ = ["SNESState", "snes", "snes_ask", "snes_tell"]


@pytree_dataclass
class SNESState:
    center: jnp.ndarray
    stdev: jnp.ndarray
    center_learning_rate: jnp.ndarray
    stdev_learning_rate: jnp.ndarray
    ranking_method: str = static_field()
    maximize: bool = static_field()


def snes(
    *,
    center_init,
    objective_sense: str,
    stdev_init: Optional[Union[float, jnp.ndarray]] = None,
    radius_init: Optional[Union[float, jnp.ndarray]] = None,
    center_learning_rate: Optional[float] = None,
    stdev_learning_rate: Optional[float] = None,
    ranking_method: str = "nes",
) -> SNESState:
    """Initial SNES state with the reference's learning-rate heuristics
    (popsize-independent; ``0.2 * (3 + log n) / sqrt(n)``)."""
    center_init = jnp.asarray(center_init)
    n = center_init.shape[-1]
    if objective_sense not in ("min", "max"):
        raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if radius_init is not None:
        # radius may be batched (one radius per search lane)
        stdev_init = jnp.asarray(radius_init, dtype=center_init.dtype) / jnp.sqrt(
            jnp.asarray(n, dtype=center_init.dtype)
        )
    if center_learning_rate is None:
        center_learning_rate = 1.0
    if stdev_learning_rate is None:
        stdev_learning_rate = 0.2 * (3 + math.log(n)) / math.sqrt(n)
    return SNESState(
        center=center_init,
        stdev=jnp.broadcast_to(jnp.asarray(stdev_init, dtype=center_init.dtype)[..., None]
            if jnp.asarray(stdev_init).ndim == center_init.ndim - 1 and jnp.asarray(stdev_init).ndim > 0
            else as_vector_like(stdev_init, center_init, 0.0), center_init.shape),
        center_learning_rate=jnp.asarray(center_learning_rate, dtype=center_init.dtype),
        stdev_learning_rate=jnp.asarray(stdev_learning_rate, dtype=center_init.dtype),
        ranking_method=str(ranking_method),
        maximize=(objective_sense == "max"),
    )


def default_popsize(solution_length: int) -> int:
    """``4 + floor(3 log n)`` (reference ``gaussian.py:948``)."""
    return int(4 + math.floor(3 * math.log(solution_length)))


def snes_ask(key, state: SNESState, *, popsize: int) -> jnp.ndarray:
    return ExpSeparableGaussian.functional_sample(
        int(popsize), {"mu": state.center, "sigma": state.stdev}, key=key
    )


def snes_tell(state: SNESState, values, evals) -> SNESState:
    grad_fn = make_functional_grad_estimator(
        ExpSeparableGaussian,
        objective_sense=("max" if state.maximize else "min"),
        ranking_method=state.ranking_method,
    )
    grads = grad_fn(values, evals, {"mu": state.center, "sigma": state.stdev})
    center = state.center + state.center_learning_rate[..., None] * grads["mu"]
    stdev = state.stdev * jnp.exp(
        0.5 * state.stdev_learning_rate[..., None] * grads["sigma"]
    )
    return replace(state, center=center, stdev=stdev)
