"""OO CMA-ES wrapper over the functional core.

Parity: reference ``algorithms/cmaes.py:90-606`` (GPU-vectorized CMA-ES based
on pycma r3.2.2). The math lives in
``algorithms/functional/funccmaes.py`` — here we wire it to the Problem /
SolutionBatch / status machinery. ``PyCMAES`` (the reference's wrapper around
the external ``cma`` package, ``pycmaes.py:39-286``) is provided as an
import-gated compatibility shim.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import Problem, Solution, SolutionBatch
from .functional.funccmaes import CMAESState, cmaes, cmaes_ask, cmaes_tell
from .searchalgorithm import SearchAlgorithm, SinglePopulationAlgorithmMixin

__all__ = ["CMAES", "PyCMAES"]


class CMAES(SearchAlgorithm, SinglePopulationAlgorithmMixin):
    """Covariance Matrix Adaptation Evolution Strategy
    (reference ``cmaes.py:90``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        stdev_init: float,
        popsize: Optional[int] = None,
        center_init=None,
        c_m: float = 1.0,
        c_sigma: Optional[float] = None,
        c_sigma_ratio: float = 1.0,
        damp_sigma: Optional[float] = None,
        damp_sigma_ratio: float = 1.0,
        c_c: Optional[float] = None,
        c_c_ratio: float = 1.0,
        c_1: Optional[float] = None,
        c_1_ratio: float = 1.0,
        c_mu: Optional[float] = None,
        c_mu_ratio: float = 1.0,
        active: bool = True,
        csa_squared: bool = False,
        stdev_min: Optional[float] = None,
        stdev_max: Optional[float] = None,
        separable: bool = False,
        limit_C_decomposition: bool = True,
        obj_index: Optional[int] = None,
    ):
        problem.ensure_numeric()
        SearchAlgorithm.__init__(
            self, problem, center=self._get_center, stdev=self._get_sigma
        )
        self._obj_index = problem.normalize_obj_index(obj_index)

        if center_init is None:
            center_init = problem.generate_values(1).reshape(-1)
        elif isinstance(center_init, Solution):
            center_init = jnp.asarray(center_init.values)
        else:
            center_init = problem.ensure_tensor_length_and_dtype(
                center_init, allow_scalar=False, about="center_init"
            )

        self._state: CMAESState = cmaes(
            center_init=center_init,
            stdev_init=float(stdev_init),
            objective_sense=problem.senses[self._obj_index],
            popsize=popsize,
            c_m=c_m,
            c_sigma=c_sigma,
            c_sigma_ratio=c_sigma_ratio,
            damp_sigma=damp_sigma,
            damp_sigma_ratio=damp_sigma_ratio,
            c_c=c_c,
            c_c_ratio=c_c_ratio,
            c_1=c_1,
            c_1_ratio=c_1_ratio,
            c_mu=c_mu,
            c_mu_ratio=c_mu_ratio,
            active=active,
            csa_squared=csa_squared,
            stdev_min=stdev_min,
            stdev_max=stdev_max,
            separable=separable,
            limit_C_decomposition=limit_C_decomposition,
        )
        self.popsize = self._state.popsize
        self._population = problem.generate_batch(self._state.popsize, empty=True)
        SinglePopulationAlgorithmMixin.__init__(self)

    @property
    def population(self) -> SolutionBatch:
        return self._population

    @property
    def state(self) -> CMAESState:
        return self._state

    @property
    def obj_index(self) -> int:
        return self._obj_index

    def _get_center(self):
        return self._state.m

    def _get_sigma(self) -> float:
        return float(self._state.sigma)

    def _step(self):
        state, xs = cmaes_ask(self._problem.next_rng_key(), self._state)
        self._population.set_values(xs)
        self._problem.evaluate(self._population)
        fitnesses = self._population.evals[:, self._obj_index]
        self._state = cmaes_tell(state, xs, fitnesses)


class PyCMAES(SearchAlgorithm, SinglePopulationAlgorithmMixin):
    """Wrapper around the external ``cma`` package's ask/tell
    (reference ``pycmaes.py:39-286``); the population crosses through numpy.
    Requires ``pip``-installed ``cma`` (not baked into the TPU image, so this
    raises ImportError when unavailable)."""

    def __init__(
        self,
        problem: Problem,
        *,
        stdev_init: float,
        popsize: Optional[int] = None,
        center_init=None,
        obj_index: Optional[int] = None,
        cma_options: Optional[dict] = None,
    ):
        import cma  # gated import

        problem.ensure_numeric()
        SearchAlgorithm.__init__(self, problem)
        self._obj_index = problem.normalize_obj_index(obj_index)
        if center_init is None:
            center_init = problem.generate_values(1).reshape(-1)
        x0 = np.asarray(center_init, dtype=np.float64)
        opts = dict(cma_options or {})
        if popsize is not None:
            opts["popsize"] = int(popsize)
        self._es = cma.CMAEvolutionStrategy(x0, float(stdev_init), opts)
        self._population = problem.generate_batch(self._es.popsize, empty=True)
        SinglePopulationAlgorithmMixin.__init__(self)

    @property
    def population(self) -> SolutionBatch:
        return self._population

    def _step(self):
        asked = self._es.ask()
        xs = jnp.asarray(np.asarray(asked), dtype=self._problem.dtype)
        self._population.set_values(xs)
        self._problem.evaluate(self._population)
        fitnesses = np.asarray(self._population.evals[:, self._obj_index], dtype=np.float64)
        sense = self._problem.senses[self._obj_index]
        if sense == "max":
            fitnesses = -fitnesses
        self._es.tell(asked, list(fitnesses))
