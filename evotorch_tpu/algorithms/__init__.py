"""Search algorithms (L5).

Parity: reference ``algorithms/__init__.py`` — distribution-based searchers
(PGPE, SNES, XNES, CEM, CMAES, PyCMAES), population-based searchers
(GeneticAlgorithm, SteadyStateGA, Cosyne, MAPElites), restart meta-algorithms,
and the pure-functional subpackage.
"""

from . import functional
from .cmaes import CMAES, PyCMAES
from .ga import Cosyne, GeneticAlgorithm, SteadyStateGA
from .gaussian import CEM, PGPE, SNES, XNES, GaussianSearchAlgorithm
from .mapelites import MAPElites
from .restarter import IPOP, ModifyingRestart, Restart
from .searchalgorithm import SearchAlgorithm, SinglePopulationAlgorithmMixin

__all__ = [
    "functional",
    "CMAES",
    "PyCMAES",
    "Cosyne",
    "GeneticAlgorithm",
    "SteadyStateGA",
    "CEM",
    "PGPE",
    "SNES",
    "XNES",
    "GaussianSearchAlgorithm",
    "MAPElites",
    "IPOP",
    "ModifyingRestart",
    "Restart",
    "SearchAlgorithm",
    "SinglePopulationAlgorithmMixin",
]
