"""Search-algorithm base machinery.

Parity: reference ``algorithms/searchalgorithm.py`` — ``LazyReporter``
(``searchalgorithm.py:34-238``), ``SearchAlgorithm`` with hooks and
``step()``/``run()`` orchestration (``searchalgorithm.py:240-447``), and
``SinglePopulationAlgorithmMixin`` auto status (``searchalgorithm.py:450-584``).
"""

from __future__ import annotations

from datetime import datetime
from functools import partial
from typing import Optional

import numpy as np

from ..core import Problem
from ..observability import counters, ensure_compile_counter, ensure_compile_timer
from ..observability.tracer import span
from ..tools.hook import Hook
from ..tools.lazyreporter import LazyReporter, LazyStatusDict

__all__ = [
    "LazyReporter",
    "LazyStatusDict",
    "SearchAlgorithm",
    "SinglePopulationAlgorithmMixin",
]


class SearchAlgorithm(LazyReporter):
    """Base class of all search algorithms (reference
    ``searchalgorithm.py:240``): hooks, step orchestration, run loop."""

    def __init__(self, problem: Problem, **kwargs):
        super().__init__(**kwargs)
        # session-wide compile accounting (observability.registry): from the
        # first searcher on, every XLA compile in the process increments the
        # `compiles` counter and accumulates its wall time into
        # `compile_seconds` — step() publishes the per-generation deltas, so
        # a steady-state retrace is visible (count AND cost) in every logger
        # for free
        ensure_compile_counter()
        ensure_compile_timer()
        self._problem = problem
        self._before_step_hook = Hook()
        self._after_step_hook = Hook()
        self._log_hook = Hook()
        self._end_of_run_hook = Hook()
        self._steps_count = 0
        self._first_step_datetime: Optional[datetime] = None
        self._problem_status_keys: tuple = ()

    # ---- problem-status passthrough (lazy; lowest precedence) --------------
    # The problem's status merges into the algorithm's WITHOUT materializing
    # device-resident entries. Precedence: _computed (update_status results,
    # incl. after-step hooks) > _getters (algorithm getters) > problem keys —
    # so hooks can still override problem-reported values. Reads memoize into
    # _computed, pinning the value for the rest of the step.
    def get_status_value(self, key: str):
        try:
            return super().get_status_value(key)
        except KeyError:
            if key in self._problem_status_keys:
                value = self._problem.get_status_value(key)
                self._computed[key] = value
                return value
            raise

    def has_status_key(self, key: str) -> bool:
        return super().has_status_key(key) or key in self._problem_status_keys

    def iter_status_keys(self):
        seen = set()
        for k in super().iter_status_keys():
            seen.add(k)
            yield k
        for k in self._problem_status_keys:
            if k not in seen:
                yield k

    @property
    def problem(self) -> Problem:
        return self._problem

    @property
    def before_step_hook(self) -> Hook:
        return self._before_step_hook

    @property
    def after_step_hook(self) -> Hook:
        return self._after_step_hook

    @property
    def log_hook(self) -> Hook:
        return self._log_hook

    @property
    def end_of_run_hook(self) -> Hook:
        return self._end_of_run_hook

    @property
    def step_count(self) -> int:
        return self._steps_count

    @property
    def steps_count(self) -> int:  # legacy alias (reference keeps both)
        return self._steps_count

    @property
    def first_step_datetime(self) -> Optional[datetime]:
        return self._first_step_datetime

    @property
    def is_terminated(self) -> bool:
        """Overridable termination criterion (reference
        ``searchalgorithm.py:445``)."""
        return False

    def _step(self):
        raise NotImplementedError

    def step(self):
        """One generation (reference ``searchalgorithm.py:380-397``).
        Beyond the reference, per-generation wall-clock is published as
        ``step_seconds``, and the observability registry's per-step deltas
        as ``compiles`` / ``trace_spans`` / ``telemetry_fetches`` /
        ``compile_seconds`` (compile-pipeline wall time this generation) —
        a nonzero ``compiles`` after warmup IS a steady-state retrace, and
        ``compile_seconds`` says what it cost. ``peak_hbm_bytes`` is the
        program ledger's high-water gauge (the largest analyzed peak
        footprint captured so far; 0 until something is captured —
        docs/observability.md "Program ledger")."""
        import time

        self._before_step_hook()
        self.clear_status()
        if self._first_step_datetime is None:
            self._first_step_datetime = datetime.now()
        meters = counters.snapshot(
            ("compiles", "trace_spans", "telemetry_fetches", "compile_seconds")
        )
        t0 = time.perf_counter()
        with span("generation", "algo", n=self._steps_count + 1):
            self._step()
        step_seconds = time.perf_counter() - t0
        self._steps_count += 1
        self.update_status({"iter": self._steps_count, "step_seconds": step_seconds})
        self.update_status(counters.delta(meters))
        # absolute gauges (not per-step deltas): the ledger's peak-footprint
        # high-water mark, so every logger row carries the memory figure
        self.update_status({"peak_hbm_bytes": counters.get("peak_hbm_bytes")})
        # refresh the lazy problem-status passthrough (see get_status_value)
        self._problem_status_keys = tuple(self._problem.iter_status_keys())
        extra = self._after_step_hook.accumulate_dict()
        if extra:
            self.update_status(extra)
        if len(self._log_hook) >= 1:
            self._log_hook(dict(self.status.items()))

    def run(
        self,
        num_generations: int,
        *,
        reset_first_step_datetime: bool = True,
        profile_dir: Optional[str] = None,
    ):
        """Run ``num_generations`` steps (reference ``searchalgorithm.py:409``).

        ``profile_dir`` captures a ``jax.profiler`` device trace of the whole
        run (SURVEY.md §5: the reference has no tracing; on TPU this is how
        you see MXU/HBM utilization and host<->device gaps). View with
        ``tensorboard --logdir <profile_dir>`` or xprof."""
        if reset_first_step_datetime:
            self.reset_first_step_datetime()

        def _run():
            for _ in range(int(num_generations)):
                self.step()
                if self.is_terminated:
                    break

        if profile_dir is not None:
            import jax

            with jax.profiler.trace(str(profile_dir)):
                _run()
        else:
            _run()
        if len(self._end_of_run_hook) >= 1:
            self._end_of_run_hook(dict(self.status.items()))

    def reset_first_step_datetime(self):
        self._first_step_datetime = None


class SinglePopulationAlgorithmMixin:
    """Auto status getters over ``.population``
    (reference ``searchalgorithm.py:450-584``): ``pop_best``,
    ``pop_best_eval``, ``mean_eval``, ``median_eval`` (prefixed per objective
    in the multi-objective case)."""

    def __init__(self, *, exclude: Optional[set] = None, enable: bool = True):
        if not enable:
            return
        exclude = exclude or set()
        problem = self.problem

        from functools import partial

        def make_getters(obj_index: int, prefix: str):
            # partials over bound methods (not closures) keep searchers
            # picklable for whole-object checkpointing
            return {
                f"{prefix}pop_best": partial(self._status_pop_best, obj_index),
                f"{prefix}pop_best_eval": partial(self._status_pop_best_eval, obj_index),
                f"{prefix}mean_eval": partial(self._status_mean_eval, obj_index),
                f"{prefix}median_eval": partial(self._status_median_eval, obj_index),
            }

        # algorithms focused on a single objective (via their obj_index)
        # report unprefixed stats for that objective even on multi-objective
        # problems (reference searchalgorithm.py:563-574); only truly
        # multi-objective algorithms get per-objective prefixes
        algo_obj_index = getattr(self, "obj_index", None)
        if problem.is_multi_objective and algo_obj_index is None:
            getters = {}
            for i in range(problem.num_objectives):
                getters.update(make_getters(i, f"obj{i}_"))
        else:
            getters = make_getters(0 if algo_obj_index is None else int(algo_obj_index), "")
        self.update_status_getters({k: v for k, v in getters.items() if k not in exclude})

    def _status_pop_best(self, obj_index: int):
        batch = self.population
        i = int(np.asarray(batch.argbest(obj_index)))
        return batch[i].clone()

    def _status_pop_best_eval(self, obj_index: int) -> float:
        batch = self.population
        i = int(np.asarray(batch.argbest(obj_index)))
        return float(np.asarray(batch.evals[i, obj_index]))

    def _status_mean_eval(self, obj_index: int) -> float:
        return float(np.nanmean(np.asarray(self.population.evals[:, obj_index])))

    def _status_median_eval(self, obj_index: int) -> float:
        return float(np.nanmedian(np.asarray(self.population.evals[:, obj_index])))
