"""Search-algorithm base machinery.

Parity: reference ``algorithms/searchalgorithm.py`` — ``LazyReporter``
(``searchalgorithm.py:34-238``), ``SearchAlgorithm`` with hooks and
``step()``/``run()`` orchestration (``searchalgorithm.py:240-447``), and
``SinglePopulationAlgorithmMixin`` auto status (``searchalgorithm.py:450-584``).
"""

from __future__ import annotations

from datetime import datetime
from typing import Optional

import numpy as np

from ..core import Problem
from ..tools.hook import Hook

__all__ = [
    "LazyReporter",
    "SearchAlgorithm",
    "SinglePopulationAlgorithmMixin",
]


class LazyReporter:
    """Lazy, memoized status providers (reference ``searchalgorithm.py:34``).

    Subclasses declare status items by passing ``name=getter_function`` pairs
    to ``__init__``; each getter runs at most once per step."""

    def __init__(self, **kwargs):
        self._getters: dict = {}
        self._computed: dict = {}
        self.update_status_getters(kwargs)

    def update_status_getters(self, getters: dict):
        self._getters.update(getters)

    # reference name (searchalgorithm.py uses add_status_getters)
    add_status_getters = update_status_getters

    def clear_status(self):
        self._computed = {}

    def update_status(self, additional_status: dict):
        for k, v in additional_status.items():
            if k not in self._getters:
                self._computed[k] = v

    def has_status_key(self, key: str) -> bool:
        return key in self._computed or key in self._getters

    def iter_status_keys(self):
        seen = set()
        for k in self._computed:
            seen.add(k)
            yield k
        for k in self._getters:
            if k not in seen:
                yield k

    def get_status_value(self, key: str):
        if key in self._computed:
            return self._computed[key]
        if key in self._getters:
            value = self._getters[key]()
            self._computed[key] = value
            return value
        raise KeyError(key)

    @property
    def status(self) -> "LazyStatusDict":
        return LazyStatusDict(self)


class LazyStatusDict:
    """Mapping view over a LazyReporter (reference ``searchalgorithm.py:180``)."""

    def __init__(self, reporter: LazyReporter):
        self._reporter = reporter

    def __getitem__(self, key):
        return self._reporter.get_status_value(key)

    def __contains__(self, key):
        return self._reporter.has_status_key(key)

    def __iter__(self):
        return self._reporter.iter_status_keys()

    def __len__(self):
        return sum(1 for _ in self._reporter.iter_status_keys())

    def keys(self):
        return list(iter(self))

    def items(self):
        for k in self:
            yield k, self[k]

    def values(self):
        for k in self:
            yield self[k]

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __repr__(self):
        return f"<status {self.keys()}>"


class SearchAlgorithm(LazyReporter):
    """Base class of all search algorithms (reference
    ``searchalgorithm.py:240``): hooks, step orchestration, run loop."""

    def __init__(self, problem: Problem, **kwargs):
        super().__init__(**kwargs)
        self._problem = problem
        self._before_step_hook = Hook()
        self._after_step_hook = Hook()
        self._log_hook = Hook()
        self._end_of_run_hook = Hook()
        self._steps_count = 0
        self._first_step_datetime: Optional[datetime] = None

    @property
    def problem(self) -> Problem:
        return self._problem

    @property
    def before_step_hook(self) -> Hook:
        return self._before_step_hook

    @property
    def after_step_hook(self) -> Hook:
        return self._after_step_hook

    @property
    def log_hook(self) -> Hook:
        return self._log_hook

    @property
    def end_of_run_hook(self) -> Hook:
        return self._end_of_run_hook

    @property
    def step_count(self) -> int:
        return self._steps_count

    @property
    def steps_count(self) -> int:  # legacy alias (reference keeps both)
        return self._steps_count

    @property
    def first_step_datetime(self) -> Optional[datetime]:
        return self._first_step_datetime

    @property
    def is_terminated(self) -> bool:
        """Overridable termination criterion (reference
        ``searchalgorithm.py:445``)."""
        return False

    def _step(self):
        raise NotImplementedError

    def step(self):
        """One generation (reference ``searchalgorithm.py:380-397``).
        Beyond the reference, per-generation wall-clock is published as
        ``step_seconds`` (SURVEY.md §5: the reference has no tracing beyond
        ``first_step_datetime``)."""
        import time

        self._before_step_hook()
        self.clear_status()
        if self._first_step_datetime is None:
            self._first_step_datetime = datetime.now()
        t0 = time.perf_counter()
        self._step()
        step_seconds = time.perf_counter() - t0
        self._steps_count += 1
        self.update_status({"iter": self._steps_count, "step_seconds": step_seconds})
        self.update_status(self._problem.status)
        extra = self._after_step_hook.accumulate_dict()
        if extra:
            self.update_status(extra)
        if len(self._log_hook) >= 1:
            self._log_hook(dict(self.status.items()))

    def run(self, num_generations: int, *, reset_first_step_datetime: bool = True):
        """Run ``num_generations`` steps (reference ``searchalgorithm.py:409``)."""
        if reset_first_step_datetime:
            self.reset_first_step_datetime()
        for _ in range(int(num_generations)):
            self.step()
            if self.is_terminated:
                break
        if len(self._end_of_run_hook) >= 1:
            self._end_of_run_hook(dict(self.status.items()))

    def reset_first_step_datetime(self):
        self._first_step_datetime = None


class SinglePopulationAlgorithmMixin:
    """Auto status getters over ``.population``
    (reference ``searchalgorithm.py:450-584``): ``pop_best``,
    ``pop_best_eval``, ``mean_eval``, ``median_eval`` (prefixed per objective
    in the multi-objective case)."""

    def __init__(self, *, exclude: Optional[set] = None, enable: bool = True):
        if not enable:
            return
        exclude = exclude or set()
        problem = self.problem

        from functools import partial

        def make_getters(obj_index: int, prefix: str):
            # partials over bound methods (not closures) keep searchers
            # picklable for whole-object checkpointing
            return {
                f"{prefix}pop_best": partial(self._status_pop_best, obj_index),
                f"{prefix}pop_best_eval": partial(self._status_pop_best_eval, obj_index),
                f"{prefix}mean_eval": partial(self._status_mean_eval, obj_index),
                f"{prefix}median_eval": partial(self._status_median_eval, obj_index),
            }

        # algorithms focused on a single objective (via their obj_index)
        # report unprefixed stats for that objective even on multi-objective
        # problems (reference searchalgorithm.py:563-574); only truly
        # multi-objective algorithms get per-objective prefixes
        algo_obj_index = getattr(self, "obj_index", None)
        if problem.is_multi_objective and algo_obj_index is None:
            getters = {}
            for i in range(problem.num_objectives):
                getters.update(make_getters(i, f"obj{i}_"))
        else:
            getters = make_getters(0 if algo_obj_index is None else int(algo_obj_index), "")
        self.update_status_getters({k: v for k, v in getters.items() if k not in exclude})

    def _status_pop_best(self, obj_index: int):
        batch = self.population
        i = int(np.asarray(batch.argbest(obj_index)))
        return batch[i].clone()

    def _status_pop_best_eval(self, obj_index: int) -> float:
        batch = self.population
        i = int(np.asarray(batch.argbest(obj_index)))
        return float(np.asarray(batch.evals[i, obj_index]))

    def _status_mean_eval(self, obj_index: int) -> float:
        return float(np.nanmean(np.asarray(self.population.evals[:, obj_index])))

    def _status_median_eval(self, obj_index: int) -> float:
        return float(np.nanmedian(np.asarray(self.population.evals[:, obj_index])))
