"""Meta-algorithms: independent restarts.

Parity: reference ``algorithms/restarter/`` — ``Restart``
(``restart.py:21-74``), ``ModifyingRestart`` / ``IPOP``
(``modify_restart.py:23-72``). These are *algorithmic* restarts on search
stagnation, not fault tolerance (SURVEY.md §5).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Optional, Type

import numpy as np

from ..core import Problem
from .searchalgorithm import SearchAlgorithm

__all__ = ["Restart", "ModifyingRestart", "IPOP"]


class Restart(SearchAlgorithm):
    """Re-instantiate the inner algorithm whenever it terminates
    (reference ``restart.py:21``)."""

    def __init__(
        self,
        problem: Problem,
        algorithm_class: Type[SearchAlgorithm],
        algorithm_args: Optional[dict] = None,
        **kwargs: Any,
    ):
        SearchAlgorithm.__init__(
            self,
            problem,
            search_algorithm=self._get_sa_status,
            num_restarts=self._get_num_restarts,
            algorithm_terminated=self._search_algorithm_terminated,
            **kwargs,
        )
        self._algorithm_class = algorithm_class
        self._algorithm_args = dict(algorithm_args or {})
        self.num_restarts = 0
        self._restart()

    def _get_sa_status(self) -> dict:
        return dict(self.search_algorithm.status.items())

    def _get_num_restarts(self) -> int:
        return self.num_restarts

    def _restart(self):
        self.search_algorithm = self._algorithm_class(self._problem, **self._algorithm_args)
        self.num_restarts += 1

    def _search_algorithm_terminated(self) -> bool:
        return self.search_algorithm.is_terminated

    def _step(self):
        self.search_algorithm.step()
        if self._search_algorithm_terminated():
            self._restart()


class ModifyingRestart(Restart):
    """Restart with a chance to adjust the inner algorithm's arguments
    (reference ``modify_restart.py:23``)."""

    def _modify_algorithm_args(self):
        pass

    def _restart(self):
        self._modify_algorithm_args()
        super()._restart()


class IPOP(ModifyingRestart):
    """Increasing-population restart: when the population's fitness stdev
    collapses, restart with a multiplied popsize
    (reference ``modify_restart.py:34-72``)."""

    def __init__(
        self,
        problem: Problem,
        algorithm_class: Type[SearchAlgorithm],
        algorithm_args: Optional[dict] = None,
        min_fitness_stdev: float = 1e-9,
        popsize_multiplier: float = 2,
    ):
        super().__init__(problem, algorithm_class, algorithm_args)
        self.min_fitness_stdev = float(min_fitness_stdev)
        self.popsize_multiplier = float(popsize_multiplier)

    def _search_algorithm_terminated(self) -> bool:
        evals = np.asarray(self.search_algorithm.population.evals)
        if np.nanstd(evals) < getattr(self, "min_fitness_stdev", 1e-9):
            return True
        return super()._search_algorithm_terminated()

    def _modify_algorithm_args(self):
        if self.num_restarts >= 1:
            new_args = deepcopy(self._algorithm_args)
            new_args["popsize"] = int(
                self.popsize_multiplier * len(self.search_algorithm.population)
            )
            self._algorithm_args = new_args
