"""Distribution-based searchers: the shared Gaussian engine and
PGPE / SNES / CEM / XNES.

Parity: reference ``algorithms/distributed/gaussian.py`` —
``GaussianSearchAlgorithm`` (``gaussian.py:35-500``: non-distributed step
``gaussian.py:274-367``, distributed step ``gaussian.py:199-272``, controlled
sigma update ``gaussian.py:369-419``), ``PGPE`` (``gaussian.py:503-743``),
``SNES`` (``gaussian.py:746-983``), ``CEM`` (``gaussian.py:986-1180``),
``XNES`` (``gaussian.py:1183-1405``).

TPU notes: "distributed" here no longer means Ray actors — with
``distributed=True`` the step calls ``problem.sample_and_compute_gradients``
whose sharded form runs the sample/eval/rank/grad pipeline over the device
mesh with a ``pmean`` reduction (see ``evotorch_tpu.parallel.grad``). The
adaptive-popsize loop driven by ``num_interactions`` (``gaussian.py:296-349``)
is host-side control flow around jitted evaluations, exactly as the reference
runs it around torch kernels.
"""

from __future__ import annotations

import math
from copy import deepcopy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import Problem, SolutionBatch
from ..observability.tracer import span
from ..distributions import (
    Distribution,
    ExpGaussian,
    ExpSeparableGaussian,
    SeparableGaussian,
    SymmetricSeparableGaussian,
)
from ..optimizers import get_optimizer_class
from ..tools.misc import modify_tensor, to_stdev_init
from .searchalgorithm import SearchAlgorithm, SinglePopulationAlgorithmMixin

__all__ = ["GaussianSearchAlgorithm", "PGPE", "SNES", "CEM", "XNES"]


class GaussianSearchAlgorithm(SearchAlgorithm, SinglePopulationAlgorithmMixin):
    """Shared engine for PGPE/SNES/CEM/XNES (reference ``gaussian.py:35``)."""

    DISTRIBUTION_TYPE = NotImplemented
    DISTRIBUTION_PARAMS: Optional[dict] = None

    def __init__(
        self,
        problem: Problem,
        *,
        popsize: int,
        center_learning_rate: float,
        stdev_learning_rate: float,
        stdev_init=None,
        radius_init=None,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        optimizer=None,
        optimizer_config: Optional[dict] = None,
        ranking_method: Optional[str] = None,
        center_init=None,
        stdev_min=None,
        stdev_max=None,
        stdev_max_change=None,
        obj_index: Optional[int] = None,
        distributed: bool = False,
        popsize_weighted_grad_avg: Optional[bool] = None,
        ensure_even_popsize: bool = False,
        lowrank_rank: Optional[int] = None,
    ):
        problem.ensure_numeric()
        problem.ensure_unbounded()

        SearchAlgorithm.__init__(
            self,
            problem,
            center=self._get_mu,
            stdev=self._get_sigma,
            mean_eval=self._get_mean_eval,
        )

        self._ensure_even_popsize = bool(ensure_even_popsize)
        if self._ensure_even_popsize and popsize % 2 != 0:
            raise ValueError(f"popsize must be even, got {popsize}")

        if not distributed and num_interactions is not None:
            self.add_status_getters({"popsize": self._get_popsize})

        if center_init is None:
            mu = problem.generate_values(1).reshape(-1)
        else:
            mu = problem.ensure_tensor_length_and_dtype(
                center_init, allow_scalar=False, about="center_init"
            )

        stdev_init = to_stdev_init(
            solution_length=problem.solution_length, stdev_init=stdev_init, radius_init=radius_init
        )
        sigma = problem.ensure_tensor_length_and_dtype(stdev_init, about="stdev_init")

        dist_cls = self.DISTRIBUTION_TYPE
        dist_params = deepcopy(self.DISTRIBUTION_PARAMS) if self.DISTRIBUTION_PARAMS is not None else {}
        dist_params.update({"mu": mu, "sigma": sigma})
        self._distribution: Distribution = dist_cls(dist_params, dtype=problem.dtype)

        # factored (low-rank) population mode: the MXU path for wide policies
        # (tools/lowrank.py; sampling + gradients on the distribution class)
        self._lowrank_rank = None if lowrank_rank is None else int(lowrank_rank)
        if self._lowrank_rank is not None:
            if self._lowrank_rank < 1:
                raise ValueError(f"lowrank_rank must be >= 1, got {lowrank_rank}")
            if not hasattr(dist_cls, "_sample_lowrank"):
                raise ValueError(
                    f"{dist_cls.__name__} has no factored sampler; "
                    "lowrank_rank requires symmetric PGPE "
                    "(SymmetricSeparableGaussian)"
                )
            # subspace-exhaustion guardrail (tools.lowrank.basis_capture):
            # every factored gradient estimate is confined to its
            # generation's rank-k basis span, so we track how much of the
            # ACCUMULATED gradient direction (an EMA over many bases — a
            # proxy for the dense gradient) each fresh basis can express.
            # A random basis captures ~sqrt(k/L) of any fixed direction;
            # persistently tiny capture means the search is mostly blind to
            # the direction it has been following — the measured failure
            # mode of the HalfCheetah rank-32 stall
            # (bench_curves/halfcheetah_lowrank_cpu_r5.jsonl).
            self._basis_capture_dev = None  # device scalar: stays lazy
            self._grad_direction_ema = None
            self._low_capture_streak = 0
            self._capture_warned = False
            # the device->host sync happens on status READ (like _mean_eval),
            # never inside the step's dispatch path
            self.add_status_getters(
                {
                    "basis_capture": lambda: (
                        None
                        if self._basis_capture_dev is None
                        else float(self._basis_capture_dev)
                    )
                }
            )

        self._popsize = int(popsize)
        self._popsize_max = None if popsize_max is None else int(popsize_max)
        self._num_interactions = None if num_interactions is None else int(num_interactions)

        self._center_learning_rate = float(center_learning_rate)
        self._stdev_learning_rate = float(stdev_learning_rate)
        self._optimizer = self._initialize_optimizer(self._center_learning_rate, optimizer, optimizer_config)
        self._ranking_method = None if ranking_method is None else str(ranking_method)

        # algorithm-health scalars (docs/observability.md "Search health"):
        # same device-scalar discipline as _mean_eval / basis_capture — the
        # update step only ENQUEUES device scalars; the host float
        # materializes when the status key is actually read
        self._center_update_norm_dev = None
        # bound methods, not lambdas: the curve runner's checkpoint bundles
        # pickle the whole searcher, and a lambda getter would break that
        self.add_status_getters(
            {
                "stdev_norm": self._get_stdev_norm,
                "center_update_norm": self._get_center_update_norm,
                "clipup_velocity_norm": self._get_clipup_velocity_norm,
            }
        )

        ensure = problem.ensure_tensor_length_and_dtype
        self._stdev_min = None if stdev_min is None else ensure(stdev_min, about="stdev_min")
        self._stdev_max = None if stdev_max is None else ensure(stdev_max, about="stdev_max")
        self._stdev_max_change = (
            None if stdev_max_change is None else ensure(stdev_max_change, about="stdev_max_change")
        )

        self._obj_index = problem.normalize_obj_index(obj_index)
        self._distributed = bool(distributed)

        if distributed:
            self._step = self._step_distributed
        else:
            self._step = self._step_non_distributed
            if popsize_weighted_grad_avg is not None:
                raise ValueError(
                    "popsize_weighted_grad_avg is only meaningful in distributed mode"
                )

        if popsize_weighted_grad_avg is None:
            self._popsize_weighted_grad_avg = num_interactions is None
        else:
            self._popsize_weighted_grad_avg = bool(popsize_weighted_grad_avg)

        self._mean_eval: Optional[float] = None
        self._population: Optional[SolutionBatch] = None
        self._first_iter = True

        SinglePopulationAlgorithmMixin.__init__(
            self, exclude={"mean_eval"}, enable=(not distributed)
        )

    # ------------------------------------------------------------ properties
    @property
    def population(self) -> SolutionBatch:
        if self._population is None:
            raise RuntimeError("The population is not ready yet; take a step first")
        return self._population

    @property
    def distribution(self) -> Distribution:
        return self._distribution

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def obj_index(self) -> int:
        return self._obj_index

    def _get_mu(self):
        return self._distribution.parameters["mu"]

    def _get_sigma(self):
        sigma = self._distribution.parameters["sigma"]
        return sigma

    def _get_mean_eval(self):
        # _mean_eval is kept as a device scalar (no sync in the hot loop);
        # the host float materializes only when the status is actually read
        return None if self._mean_eval is None else float(self._mean_eval)

    def _get_stdev_norm(self):
        # computed on READ from the current distribution parameters — no
        # per-step bookkeeping, and value-identical to the host-side
        # jnp.linalg.norm(status["stdev"]) it replaces in the examples
        return float(jnp.linalg.norm(self._distribution.parameters["sigma"]))

    def _get_center_update_norm(self):
        return (
            None
            if self._center_update_norm_dev is None
            else float(self._center_update_norm_dev)
        )

    def _get_clipup_velocity_norm(self):
        velocity = getattr(self._optimizer, "_velocity", None)
        return None if velocity is None else float(jnp.linalg.norm(velocity))

    def _get_popsize(self):
        return 0 if self._population is None else len(self._population)

    # -------------------------------------------------------------- plumbing
    def _initialize_optimizer(self, learning_rate, optimizer, optimizer_config):
        if optimizer is None:
            return None
        if isinstance(optimizer, str):
            cls = get_optimizer_class(optimizer, optimizer_config)
            return cls(
                stepsize=float(learning_rate),
                dtype=self._distribution.dtype,
                solution_length=self._distribution.solution_length,
            )
        return optimizer

    def _step(self):  # replaced in __init__
        raise NotImplementedError

    # -------------------------------------------------------- non-distributed
    def _sample_population(self, popsize: int, *, basis=None) -> SolutionBatch:
        if self._lowrank_rank is not None:
            samples = self._distribution.sample_lowrank(
                popsize,
                self._lowrank_rank,
                key=self._problem.next_rng_key(),
                basis=basis,
            )
            return SolutionBatch(self._problem, values=samples)
        samples = self._distribution.sample(popsize, key=self._problem.next_rng_key())
        return SolutionBatch(self._problem, samples.shape[0], values=samples)

    def _fill_and_eval_pop(self):
        """Sample + evaluate, with the adaptive-popsize loop when
        ``num_interactions`` is configured (reference ``gaussian.py:276-349``).
        In factored (low-rank) mode the generation's first round draws the
        basis and every later round samples fresh coefficients against it, so
        the per-round batches stay concatenable (SolutionBatch.cat of
        shared-basis factored batches)."""
        problem = self._problem
        if self._num_interactions is None:
            with span("ask", "algo"):
                self._population = self._sample_population(self._popsize)
            with span("eval", "algo", popsize=self._popsize):
                problem.evaluate(self._population)
            return
        first_count = int(problem.status.get("total_interaction_count", 0))
        batches = []
        total_popsize = 0
        prev_made = -1
        gen_basis = None
        while True:
            with span("ask", "algo"):
                batch = self._sample_population(self._popsize, basis=gen_basis)
            if self._lowrank_rank is not None and gen_basis is None:
                gen_basis = batch.values.basis
            with span("eval", "algo", popsize=len(batch)):
                problem.evaluate(batch)
            batches.append(batch)
            total_popsize += len(batch)
            if self._popsize_max is not None and total_popsize >= self._popsize_max:
                break
            interactions_made = int(problem.status.get("total_interaction_count", 0)) - first_count
            if interactions_made > self._num_interactions:
                break
            if "total_interaction_count" not in problem.status:
                break  # the problem does not report interactions; avoid looping forever
            if interactions_made <= prev_made:
                break  # counter stopped advancing; the budget is unreachable
            prev_made = interactions_made
        self._population = batches[0] if len(batches) == 1 else SolutionBatch.cat(batches)

    # capture below this for _CAPTURE_WARN_STREAK consecutive generations =>
    # subspace exhaustion warning. 0.1 sits between sqrt(k/L) of configs
    # measured to stall (HalfCheetah rank 32 at L~5.8k: 0.074) and configs
    # measured to train through (rank 64: 0.105).
    _CAPTURE_WARN_THRESHOLD = 0.1
    _CAPTURE_WARN_STREAK = 3

    def _update_basis_capture(self, basis, mu_grad):
        """Track the fraction of the accumulated gradient direction the
        CURRENT generation's basis spans, and warn once on persistent
        subspace exhaustion (see the constructor commentary).

        Device-scalar discipline (VERDICT r1 item 6: no device->host sync in
        the hot loop): each generation ENQUEUES its capture as a device
        scalar and host-processes the PREVIOUS generation's — that scalar's
        dispatch has retired behind the current generation's work, so the
        ``float()`` is a cheap transfer, not a pipeline stall. The streak
        bookkeeping and the warning therefore lag one generation."""
        import warnings

        from ..tools.lowrank import basis_capture

        prev = self._basis_capture_dev
        if prev is not None:
            capture = float(prev)
            if capture < self._CAPTURE_WARN_THRESHOLD:
                self._low_capture_streak += 1
            else:
                self._low_capture_streak = 0
            if (
                self._low_capture_streak >= self._CAPTURE_WARN_STREAK
                and not self._capture_warned
            ):
                self._capture_warned = True
                L = int(self._distribution.solution_length)
                warnings.warn(
                    "factored (low-rank) search subspace exhaustion: the "
                    f"rank-{self._lowrank_rank} basis captures only "
                    f"{capture:.1%} of the estimated dense gradient "
                    f"direction over {self._low_capture_streak} consecutive "
                    f"generations (random-basis expectation at L={L}: "
                    f"~{math.sqrt(self._lowrank_rank / max(L, 1)):.1%}). "
                    "Most of the gradient signal is not expressible in the "
                    "subspace and progress is likely to stall — consider "
                    "increasing lowrank_rank (status key: basis_capture).",
                    stacklevel=3,
                )
        if self._grad_direction_ema is not None:
            # enqueued lazily; read back on the NEXT generation (or on
            # status read, whichever comes first)
            self._basis_capture_dev = basis_capture(basis, self._grad_direction_ema)
        norm = jnp.linalg.norm(mu_grad)
        direction = mu_grad / jnp.maximum(norm, 1e-30)
        if self._grad_direction_ema is None:
            self._grad_direction_ema = direction
        else:
            # device-side EMA: no host sync beyond the one scalar capture read
            self._grad_direction_ema = (
                0.8 * self._grad_direction_ema + 0.2 * direction
            )

    def _step_non_distributed(self):
        """Reference ``gaussian.py:274-367``: from generation 1 on, compute
        gradients from the previous population, update the distribution, then
        resample and evaluate."""
        if self._first_iter:
            self._first_iter = False
            self._fill_and_eval_pop()
            self._mean_eval = jnp.nanmean(self._population.evals[:, self._obj_index])
            return
        pop = self._population
        samples = pop.values
        fitnesses = pop.evals[:, self._obj_index]
        obj_sense = self._problem.senses[self._obj_index]
        with span("tell", "algo"):
            with jax.profiler.TraceAnnotation("evotorch_tpu.grad"):
                grads = self._distribution.compute_gradients(
                    samples,
                    fitnesses,
                    objective_sense=obj_sense,
                    ranking_method=self._ranking_method if self._ranking_method is not None else "raw",
                )
            if self._lowrank_rank is not None:
                # basis_capture guardrail: measured against the basis the
                # gradient was just estimated in, BEFORE that gradient enters
                # the direction EMA
                self._update_basis_capture(samples.basis, grads["mu"])
            with jax.profiler.TraceAnnotation("evotorch_tpu.update"):
                self._update_distribution(grads)
        with jax.profiler.TraceAnnotation("evotorch_tpu.ask"):
            self._fill_and_eval_pop()
        self._mean_eval = jnp.nanmean(self._population.evals[:, self._obj_index])

    # ------------------------------------------------------------ distributed
    def _step_distributed(self):
        """Reference ``gaussian.py:199-272``: gather per-shard gradient dicts
        and average them (weighted by sub-population size when configured)."""
        with span("sample_and_grad", "algo"):
            results = self._problem.sample_and_compute_gradients(
                self._distribution,
                self._popsize,
                popsize_max=self._popsize_max,
                num_interactions=self._num_interactions,
                ranking_method=self._ranking_method if self._ranking_method is not None else "raw",
                obj_index=self._obj_index,
                lowrank_rank=self._lowrank_rank,
            )
        grads_list = [r["gradients"] for r in results]
        nums = np.asarray([r["num_solutions"] for r in results], dtype=np.float64)
        rel = nums / nums.sum()  # population-size weighting (host-side floats)
        weights = rel if self._popsize_weighted_grad_avg else np.full(
            len(results), 1.0 / len(results)
        )
        avg = {}
        for k in grads_list[0]:
            avg[k] = sum(w * g[k] for w, g in zip(weights, grads_list))
        # mean_eval stays a device scalar until the status is read
        self._mean_eval = sum(w * r["mean_eval"] for w, r in zip(rel, results))
        if self._lowrank_rank is not None and results[0].get("basis") is not None:
            # same guardrail as the non-distributed step; the sharded
            # estimator surfaces shard 0's basis as a representative iid
            # draw (capture statistics are exchangeable across shards)
            self._update_basis_capture(results[0]["basis"], avg["mu"])
        with span("tell", "algo"):
            self._update_distribution(avg)

    # --------------------------------------------------------------- updates
    def _update_distribution(self, gradients: dict):
        """Distribution update + controlled sigma clamping
        (reference ``gaussian.py:369-419``)."""
        learning_rates = {"mu": self._center_learning_rate, "sigma": self._stdev_learning_rate}
        optimizers = {"mu": self._optimizer} if self._optimizer is not None else None
        old_sigma = self._distribution.parameters["sigma"]
        old_mu = self._distribution.parameters["mu"]
        new_dist = self._distribution.update_parameters(
            gradients, learning_rates=learning_rates, optimizers=optimizers
        )
        # enqueued as a device scalar; synced on status read (lag-free here
        # because the read happens after the step's dispatch has retired)
        self._center_update_norm_dev = jnp.linalg.norm(
            new_dist.parameters["mu"] - old_mu
        )
        if (
            self._stdev_min is not None
            or self._stdev_max is not None
            or self._stdev_max_change is not None
        ):
            clamped = modify_tensor(
                old_sigma,
                new_dist.parameters["sigma"],
                lb=self._stdev_min,
                ub=self._stdev_max,
                max_change=self._stdev_max_change,
            )
            new_dist = new_dist.modified_copy(sigma=clamped)
        self._distribution = new_dist


class PGPE(GaussianSearchAlgorithm):
    """PGPE with 0-centered ranking and ClipUp, the configuration of
    Toklu et al. (2020) (reference ``gaussian.py:503-743``)."""

    DISTRIBUTION_TYPE = NotImplemented  # set per instance (symmetric or not)
    DISTRIBUTION_PARAMS = NotImplemented

    def __init__(
        self,
        problem: Problem,
        *,
        popsize: int,
        center_learning_rate: float,
        stdev_learning_rate: float,
        stdev_init=None,
        radius_init=None,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        optimizer="clipup",
        optimizer_config: Optional[dict] = None,
        ranking_method: Optional[str] = "centered",
        center_init=None,
        stdev_min=None,
        stdev_max=None,
        stdev_max_change=0.2,
        symmetric: bool = True,
        obj_index: Optional[int] = None,
        distributed: bool = False,
        popsize_weighted_grad_avg: Optional[bool] = None,
        lowrank_rank: Optional[int] = None,
    ):
        if lowrank_rank is not None and not symmetric:
            raise ValueError("lowrank_rank requires symmetric=True (the PGPE default)")
        if symmetric:
            self.DISTRIBUTION_TYPE = SymmetricSeparableGaussian
            divide_by = "num_directions"
        else:
            self.DISTRIBUTION_TYPE = SeparableGaussian
            divide_by = "num_solutions"
        self.DISTRIBUTION_PARAMS = {
            "divide_mu_grad_by": divide_by,
            "divide_sigma_grad_by": divide_by,
        }
        super().__init__(
            problem,
            popsize=popsize,
            center_learning_rate=center_learning_rate,
            stdev_learning_rate=stdev_learning_rate,
            stdev_init=stdev_init,
            radius_init=radius_init,
            popsize_max=popsize_max,
            num_interactions=num_interactions,
            optimizer=optimizer,
            optimizer_config=optimizer_config,
            ranking_method=ranking_method,
            center_init=center_init,
            stdev_min=stdev_min,
            stdev_max=stdev_max,
            stdev_max_change=stdev_max_change,
            obj_index=obj_index,
            distributed=distributed,
            popsize_weighted_grad_avg=popsize_weighted_grad_avg,
            ensure_even_popsize=symmetric,
            lowrank_rank=lowrank_rank,
        )


class SNES(GaussianSearchAlgorithm):
    """Separable NES (Schaul et al. 2011; reference ``gaussian.py:746-983``)."""

    DISTRIBUTION_TYPE = ExpSeparableGaussian
    DISTRIBUTION_PARAMS = None

    def __init__(
        self,
        problem: Problem,
        *,
        stdev_init=None,
        radius_init=None,
        popsize: Optional[int] = None,
        center_learning_rate: Optional[float] = None,
        stdev_learning_rate: Optional[float] = None,
        scale_learning_rate: bool = True,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        optimizer=None,
        optimizer_config: Optional[dict] = None,
        ranking_method: Optional[str] = "nes",
        center_init=None,
        stdev_min=None,
        stdev_max=None,
        stdev_max_change=None,
        obj_index: Optional[int] = None,
        distributed: bool = False,
        popsize_weighted_grad_avg: Optional[bool] = None,
    ):
        if popsize is None:
            popsize = int(4 + math.floor(3 * math.log(problem.solution_length)))
        if center_learning_rate is None:
            center_learning_rate = 1.0

        def default_stdev_lr():
            n = problem.solution_length
            return 0.2 * (3 + math.log(n)) / math.sqrt(n)

        if stdev_learning_rate is None:
            stdev_learning_rate = default_stdev_lr()
        else:
            stdev_learning_rate = float(stdev_learning_rate)
            if scale_learning_rate:
                stdev_learning_rate *= default_stdev_lr()

        super().__init__(
            problem,
            popsize=popsize,
            center_learning_rate=center_learning_rate,
            stdev_learning_rate=stdev_learning_rate,
            stdev_init=stdev_init,
            radius_init=radius_init,
            popsize_max=popsize_max,
            num_interactions=num_interactions,
            optimizer=optimizer,
            optimizer_config=optimizer_config,
            ranking_method=ranking_method,
            center_init=center_init,
            stdev_min=stdev_min,
            stdev_max=stdev_max,
            stdev_max_change=stdev_max_change,
            obj_index=obj_index,
            distributed=distributed,
            popsize_weighted_grad_avg=popsize_weighted_grad_avg,
        )


class CEM(GaussianSearchAlgorithm):
    """Cross-entropy method, Duan et al. (2016) variant
    (reference ``gaussian.py:986-1180``)."""

    DISTRIBUTION_TYPE = SeparableGaussian
    DISTRIBUTION_PARAMS = NotImplemented  # set per instance

    def __init__(
        self,
        problem: Problem,
        *,
        popsize: int,
        parenthood_ratio: float,
        stdev_init=None,
        radius_init=None,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        center_init=None,
        stdev_min=None,
        stdev_max=None,
        stdev_max_change=None,
        obj_index: Optional[int] = None,
        distributed: bool = False,
        popsize_weighted_grad_avg: Optional[bool] = None,
    ):
        self.DISTRIBUTION_PARAMS = {"parenthood_ratio": float(parenthood_ratio)}
        super().__init__(
            problem,
            popsize=popsize,
            center_learning_rate=1.0,
            stdev_learning_rate=1.0,
            stdev_init=stdev_init,
            radius_init=radius_init,
            popsize_max=popsize_max,
            num_interactions=num_interactions,
            optimizer=None,
            optimizer_config=None,
            ranking_method=None,
            center_init=center_init,
            stdev_min=stdev_min,
            stdev_max=stdev_max,
            stdev_max_change=stdev_max_change,
            obj_index=obj_index,
            distributed=distributed,
            popsize_weighted_grad_avg=popsize_weighted_grad_avg,
        )


class XNES(GaussianSearchAlgorithm):
    """Exponential NES with full covariance (Glasmachers et al. 2010;
    reference ``gaussian.py:1183-1405``)."""

    DISTRIBUTION_TYPE = ExpGaussian
    DISTRIBUTION_PARAMS = None

    def __init__(
        self,
        problem: Problem,
        *,
        stdev_init=None,
        radius_init=None,
        popsize: Optional[int] = None,
        center_learning_rate: Optional[float] = None,
        stdev_learning_rate: Optional[float] = None,
        scale_learning_rate: bool = True,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        optimizer=None,
        optimizer_config: Optional[dict] = None,
        ranking_method: Optional[str] = "nes",
        center_init=None,
        obj_index: Optional[int] = None,
        distributed: bool = False,
        popsize_weighted_grad_avg: Optional[bool] = None,
    ):
        if popsize is None:
            popsize = int(4 + math.floor(3 * math.log(problem.solution_length)))
        if center_learning_rate is None:
            center_learning_rate = 1.0

        def default_stdev_lr():
            n = problem.solution_length
            return 0.6 * (3 + math.log(n)) / (n * math.sqrt(n))

        if stdev_learning_rate is None:
            stdev_learning_rate = default_stdev_lr()
        else:
            stdev_learning_rate = float(stdev_learning_rate)
            if scale_learning_rate:
                stdev_learning_rate *= default_stdev_lr()

        super().__init__(
            problem,
            popsize=popsize,
            center_learning_rate=center_learning_rate,
            stdev_learning_rate=stdev_learning_rate,
            stdev_init=stdev_init,
            radius_init=radius_init,
            popsize_max=popsize_max,
            num_interactions=num_interactions,
            optimizer=optimizer,
            optimizer_config=optimizer_config,
            ranking_method=ranking_method,
            center_init=center_init,
            stdev_min=None,
            stdev_max=None,
            stdev_max_change=None,
            obj_index=obj_index,
            distributed=distributed,
            popsize_weighted_grad_avg=popsize_weighted_grad_avg,
        )
