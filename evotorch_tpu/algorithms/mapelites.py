"""MAP-Elites: quality-diversity archive over a feature hypergrid.

Parity: reference ``algorithms/mapelites.py`` — vmapped per-cell
best-solution selection (``mapelites.py:24-67``), fully vectorized ``_step``
(``mapelites.py:380-401``), ``make_feature_grid`` (``mapelites.py:403-505``).
The per-cell selection maps 1:1 onto ``jax.vmap`` and the whole selection step
is jitted.
"""

from __future__ import annotations

import itertools
from functools import partial
from typing import Iterable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import Problem, SolutionBatch
from ..tools.misc import to_jax_dtype
from .ga import ExtendedPopulationMixin
from .searchalgorithm import SearchAlgorithm, SinglePopulationAlgorithmMixin

__all__ = ["MAPElites"]


def _best_solution_considering_feature(objective_sense, decision_values, evals, feature_grid):
    """Pick, for one cell, the best solution whose features fall within the
    cell bounds (reference ``mapelites.py:24-53``)."""
    feature_lb = feature_grid[:, 0]
    feature_ub = feature_grid[:, 1]
    penalty = jnp.inf if objective_sense == "min" else -jnp.inf
    argbest = jnp.argmin if objective_sense == "min" else jnp.argmax
    fitnesses = evals[:, 0]
    features = evals[:, 1:]
    suitable = jnp.all(features >= feature_lb, axis=-1) & jnp.all(features <= feature_ub, axis=-1)
    processed = jnp.where(suitable, fitnesses, penalty)
    index = argbest(processed)
    return decision_values[index], evals[index], suitable[index]


@partial(jax.jit, static_argnames=("objective_sense",))
def _best_solutions_for_all_cells(objective_sense, decision_values, evals, feature_grid):
    """vmap over grid cells (reference ``mapelites.py:56-67``)."""
    return jax.vmap(
        lambda grid: _best_solution_considering_feature(
            objective_sense, decision_values, evals, grid
        )
    )(feature_grid)


class MAPElites(SearchAlgorithm, SinglePopulationAlgorithmMixin, ExtendedPopulationMixin):
    """MAP-Elites (reference ``mapelites.py:70``): the population is the
    archive — one solution per feature-grid cell. Requires the problem to be
    single-objective with ``eval_data_length`` equal to the number of
    features."""

    def __init__(
        self,
        problem: Problem,
        *,
        operators: Iterable,
        feature_grid: Iterable,
        re_evaluate: bool = True,
        re_evaluate_parents_first: Optional[bool] = None,
    ):
        problem.ensure_numeric()
        if problem.is_multi_objective:
            raise ValueError("MAPElites supports single-objective problems only")
        if problem.eval_data_length is None or problem.eval_data_length == 0:
            raise ValueError(
                "MAPElites requires eval_data_length >= 1 (the features of each solution)"
            )
        SearchAlgorithm.__init__(self, problem)
        self._sense = problem.senses[0]
        self._feature_grid = jnp.asarray(feature_grid, dtype=problem.eval_dtype)
        if self._feature_grid.ndim != 3 or self._feature_grid.shape[-1] != 2:
            raise ValueError(
                "feature_grid must have shape (num_cells, num_features, 2); "
                f"got {tuple(self._feature_grid.shape)}"
            )
        if self._feature_grid.shape[1] != problem.eval_data_length:
            raise ValueError(
                f"feature_grid declares {self._feature_grid.shape[1]} features but the "
                f"problem's eval_data_length is {problem.eval_data_length}"
            )
        num_cells = self._feature_grid.shape[0]
        self._population = problem.generate_batch(num_cells)
        self._filled = jnp.zeros(num_cells, dtype=bool)
        ExtendedPopulationMixin.__init__(
            self,
            re_evaluate=re_evaluate,
            re_evaluate_parents_first=re_evaluate_parents_first,
            operators=operators,
        )
        SinglePopulationAlgorithmMixin.__init__(self)

    @property
    def population(self) -> SolutionBatch:
        return self._population

    @property
    def filled(self) -> jnp.ndarray:
        """Boolean mask: ``filled[i]`` is True when the solution stored in the
        i-th cell genuinely satisfies that cell's feature bounds
        (reference ``mapelites.py:352-378``)."""
        return self._filled

    def _step(self):
        extended = self._make_extended_population(split=False)
        values, evals, suitable = _best_solutions_for_all_cells(
            self._sense,
            jnp.asarray(extended.values),
            extended.evals,
            self._feature_grid,
        )
        self._population.set_values(values, keep_evals=True)
        self._population.set_evals(evals)
        self._filled = suitable

    @staticmethod
    def make_feature_grid(
        lower_bounds: Iterable,
        upper_bounds: Iterable,
        num_bins: Union[int, Iterable[int]],
        *,
        dtype=None,
        device=None,  # accepted for API parity; placement is via shardings
    ) -> jnp.ndarray:
        """Uniform hypergrid of (num_cells, num_features, 2) bounds; outermost
        bins extend to +-inf (reference ``mapelites.py:403-505``)."""
        dtype = to_jax_dtype(dtype) if dtype is not None else jnp.float32
        lower_bounds = np.asarray(lower_bounds, dtype=np.float64)
        upper_bounds = np.asarray(upper_bounds, dtype=np.float64)
        if lower_bounds.ndim != 1 or lower_bounds.shape != upper_bounds.shape:
            raise ValueError("lower_bounds / upper_bounds must be 1-D and equal-length")
        n_features = lower_bounds.shape[0]
        if np.isscalar(num_bins) or np.asarray(num_bins).ndim == 0:
            num_bins = [int(num_bins)] * n_features
        num_bins = [int(b) for b in num_bins]
        per_feature = []
        for lb, ub, bins in zip(lower_bounds, upper_bounds, num_bins):
            edges = np.concatenate([[-np.inf], np.linspace(lb, ub, bins - 1), [np.inf]])
            intervals = np.stack([edges[:-1], edges[1:]], axis=1)  # (bins, 2)
            per_feature.append(intervals)
        cells = [
            np.stack(combo, axis=0) for combo in itertools.product(*per_feature)
        ]  # each (n_features, 2)
        return jnp.asarray(np.stack(cells), dtype=dtype)
