"""Decorators: the auto-vmap engine and fitness-function markers.

Parity: reference ``decorators.py`` — ``@vectorized`` (``decorators.py:549``),
``@expects_ndim`` (``decorators.py:613-874``), ``@rowwise``
(``decorators.py:877-965``), ``@pass_info`` (``decorators.py:170``),
``@on_device/@on_aux_device`` (``decorators.py:211-546``).

Where the reference fakes batchability with nested ``torch.func.vmap`` wraps,
JAX gives it natively: ``expects_ndim`` here broadcasts every declared arg to a
common batch shape and applies one ``jax.vmap`` over a flattened batch axis.
Device-placement decorators are retained as *markers* only — on TPU, placement
is controlled by shardings (``jax.sharding``), not per-function device moves.
"""

from __future__ import annotations

import functools
import inspect
import math
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "vectorized",
    "expects_ndim",
    "rowwise",
    "pass_info",
    "on_device",
    "on_aux_device",
    "on_cuda",
]


def vectorized(fn: Callable) -> Callable:
    """Mark a fitness function as operating on a whole ``(N, L)`` population
    (reference ``decorators.py:549-610``)."""
    fn.__evotorch_vectorized__ = True
    return fn


def pass_info(fn: Callable) -> Callable:
    """Mark a network factory as wanting problem info kwargs such as
    ``obs_length``/``act_length`` (reference ``decorators.py:170-208``)."""
    fn.__evotorch_pass_info__ = True
    return fn


def on_device(device: Any) -> Callable:
    """Marker-only parity shim for the reference's device-placement decorators
    (``decorators.py:211-546``). The returned decorator records the requested
    device; the TPU build controls placement via shardings instead."""

    def decorator(fn: Callable) -> Callable:
        fn.__evotorch_on_device__ = device
        return fn

    return decorator


def on_aux_device(fn: Optional[Callable] = None):
    if fn is None:
        return on_device("aux")
    return on_device("aux")(fn)


def on_cuda(fn: Optional[Callable] = None):
    """Marker-only parity shim for the reference's ``@on_cuda``
    (``decorators.py:350``-ish): on TPU there is no CUDA device; the marker
    maps to the accelerator device (placement is via shardings anyway)."""
    if fn is None:
        return on_device("accelerator")
    return on_device("accelerator")(fn)


def _tree_first_leaf(x):
    leaves = jax.tree_util.tree_leaves(x)
    return leaves[0] if leaves else None


def expects_ndim(
    *expected_ndims: Optional[int],
    allow_smaller_ndim: bool = False,
):
    """Declare per-positional-arg expected core ndims; extra leading dims are
    treated as batch dims and vmapped over (reference ``decorators.py:613-874``).

    ``None`` marks an argument as static (passed through untouched). Batch
    shapes of different args broadcast together, so e.g. a ``(B, L)`` center
    and a scalar stdev batch cleanly — the basis of *batched searches*
    (SURVEY.md §1, parallel API style 2).

    Reference-parity behaviors (``decorators.py:613-874``):

    - **kwargs participate**: arguments passed by keyword are bound to their
      positional slots via the function's signature, so declared ndims apply
      regardless of call style. Only arguments landing in a ``**kwargs``
      catch-all remain static.
    - **scalar/numpy coercion with dtype inference**: python scalars, lists
      and numpy arrays in declared slots are converted to jax arrays; float
      values adopt the dtype of the first floating-point jax array among the
      declared arguments (so a python-float stdev follows a bfloat16 center).

    PRNG keys passed through ``None`` slots are shared across batch lanes —
    key-consuming callers that need per-lane independence must split keys
    themselves (see ``operators.functional._apply_with_per_lane_keys``).
    """

    def decorator(fn: Callable) -> Callable:
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):  # builtins etc.: positional-only path
            sig = None

        def bind_to_positions(args, kwargs):
            """-> (positional args covering the declared slots, static
            kwargs)."""
            if sig is None or not kwargs:
                return list(args), dict(kwargs)
            positional = [
                p
                for p in sig.parameters.values()
                if p.kind
                in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()  # no gaps in the declared slots
            out_args = []
            for p in positional[: len(expected_ndims)]:
                if p.name not in bound.arguments:
                    break
                out_args.append(bound.arguments.pop(p.name))
            static = {}
            for name, value in bound.arguments.items():
                param = sig.parameters[name]
                if param.kind == param.VAR_KEYWORD:
                    static.update(value)
                elif param.kind == param.VAR_POSITIONAL:
                    if value:  # apply_defaults inserts an empty tuple
                        raise TypeError(
                            f"{fn.__name__}: expects_ndim does not support"
                            " *args functions called past the declared slots"
                        )
                else:
                    static[name] = value
            return out_args, static

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            args, kwargs = bind_to_positions(args, kwargs)
            if len(args) > len(expected_ndims):
                raise TypeError(
                    f"{fn.__name__}: got {len(args)} positional args, but "
                    f"expects_ndim declares only {len(expected_ndims)}"
                )
            # dtype inference target: the first floating jax array in a
            # declared slot
            float_dtype = None
            for arg, nd in zip(args, expected_ndims):
                if nd is None or not isinstance(arg, jax.Array):
                    continue
                if jnp.issubdtype(arg.dtype, jnp.floating):
                    float_dtype = arg.dtype
                    break
            arrs = []
            batch_shapes = []
            for arg, nd in zip(args, expected_ndims):
                if nd is None:
                    arrs.append(arg)
                    continue
                needs_coercion = not isinstance(arg, jax.Array) and isinstance(
                    arg, (int, float, bool, list, tuple, np.ndarray, np.generic)
                )
                arr = jnp.asarray(arg)
                if (
                    needs_coercion
                    and float_dtype is not None
                    and jnp.issubdtype(arr.dtype, jnp.floating)
                    and arr.dtype != float_dtype
                ):
                    arr = arr.astype(float_dtype)
                extra = arr.ndim - nd
                if extra < 0:
                    if allow_smaller_ndim:
                        arrs.append(arr)
                        continue
                    raise ValueError(
                        f"{fn.__name__}: argument with shape {arr.shape} has fewer "
                        f"than the expected {nd} dimensions"
                    )
                batch_shapes.append(arr.shape[:extra])
                arrs.append(arr)

            batch_shape = ()
            for bs in batch_shapes:
                batch_shape = jnp.broadcast_shapes(batch_shape, bs)

            if batch_shape == ():
                return fn(*arrs, **kwargs)

            batch_size = math.prod(batch_shape)
            flat_args = []
            in_axes = []
            for arg, nd in zip(arrs, expected_ndims):
                if nd is None or not hasattr(arg, "ndim"):
                    flat_args.append(arg)
                    in_axes.append(None)
                    continue
                extra = arg.ndim - nd
                if extra < 0:
                    flat_args.append(arg)
                    in_axes.append(None)
                    continue
                core_shape = arg.shape[extra:]
                full = jnp.broadcast_to(arg, batch_shape + core_shape)
                flat_args.append(full.reshape((batch_size,) + core_shape))
                in_axes.append(0)

            vfn = jax.vmap(
                functools.partial(fn, **kwargs) if kwargs else fn,
                in_axes=in_axes,
            )
            out = vfn(*flat_args)
            return jax.tree_util.tree_map(
                lambda leaf: leaf.reshape(batch_shape + leaf.shape[1:]), out
            )

        wrapped.__expects_ndim__ = expected_ndims
        return wrapped

    return decorator


def rowwise(fn: Callable) -> Callable:
    """Wrap a function written for a single 1-D row so it accepts any number of
    leading batch dims (reference ``decorators.py:877-965``). The wrapped
    function is also marked ``@vectorized`` since it can consume an ``(N, L)``
    population directly."""
    wrapped = expects_ndim(1)(fn)
    wrapped.__evotorch_rowwise__ = True
    wrapped.__evotorch_vectorized__ = True
    return wrapped
