"""Search distributions (L4): the gradient-estimation heart of the ES family.

Parity with the reference's ``distributions.py``:

- ``Distribution`` base (``distributions.py:40-410``): parameter dict, sample,
  ``compute_gradients`` (fitness ranking + delegation), ``update_parameters``,
  ``_follow_gradient`` (learning-rate or optimizer ``ascent``),
  ``modified_copy``, ``functional_sample``.
- ``SeparableGaussian`` (``distributions.py:413-613``): PGPE non-symmetric
  score-function gradients with configurable divisors; CEM-style elite update
  when ``parenthood_ratio`` is present; KL divergence.
- ``SymmetricSeparableGaussian`` (``distributions.py:616-773``): antithetic
  pairs interleaved as ``[+e0, -e0, +e1, -e1, ...]``; gradients from
  ``(f+ - f-)/2`` and ``(f+ + f-)/2``.
- ``ExpSeparableGaussian`` (``distributions.py:776-810``): SNES natural
  gradient, ``sigma <- sigma * exp(0.5 * lr * grad)``.
- ``ExpGaussian`` (``distributions.py:813-1016``): XNES full covariance via
  ``A`` with tracked ``A_inv``; updates through ``expm``.

TPU-first design: every distribution's math lives in pure classmethods over a
parameter dict (a pytree), so it jits/vmaps natively; the class instances are
thin stateful conveniences. ``make_functional_sampler`` /
``make_functional_grad_estimator`` (``distributions.py:1023-1623``) expose the
batched pure-functional API.
"""

from __future__ import annotations

from typing import Callable, Optional, Type

import jax
import jax.numpy as jnp

from .tools.cloning import Serializable
from .tools.lowrank import LowRankParamsBatch, TrunkDeltaParamsBatch, is_factored
from .tools.misc import to_jax_dtype
from .tools.ranking import rank
from .tools.recursiveprintable import RecursivePrintable
from .tools.tensormaker import TensorMakerMixin

__all__ = [
    "Distribution",
    "SeparableGaussian",
    "SymmetricSeparableGaussian",
    "ExpSeparableGaussian",
    "ExpGaussian",
    "make_functional_sampler",
    "make_functional_grad_estimator",
]

# per-class jitted kernels for the stateful (OO) API: the math lives in pure
# classmethods, so one compiled executable per (class, static-config) pair
# serves every instance and every generation
_JITTED_SAMPLE_CACHE: dict = {}
_JITTED_SAMPLE_LOWRANK_CACHE: dict = {}
_JITTED_GRADS_CACHE: dict = {}


def _split_params(parameters: dict):
    """Separate array parameters from static (string/structural) ones."""
    static = tuple(
        sorted(
            (k, v)
            for k, v in parameters.items()
            if isinstance(v, (str, type(None))) or k == "parenthood_ratio"
        )
    )
    arrays = {k: v for k, v in parameters.items() if k not in dict(static)}
    return arrays, static


def _jitted_sample_for(cls):
    # keyed on the fused-sampling flag as well as the class: _sample reads
    # EVOTORCH_TPU_FUSED_SAMPLING at trace time, so a cache hit after the env
    # var changed would silently keep serving the stale executable
    import os

    cache_key = (cls, os.environ.get("EVOTORCH_TPU_FUSED_SAMPLING", "0"))
    fn = _JITTED_SAMPLE_CACHE.get(cache_key)
    if fn is None:

        def sample(key, array_params, static_items, num_solutions):
            params = dict(array_params)
            params.update(dict(static_items))
            return cls._sample(key, params, num_solutions)

        fn = jax.jit(sample, static_argnames=("static_items", "num_solutions"))
        _JITTED_SAMPLE_CACHE[cache_key] = fn
    return fn


def _jitted_sample_lowrank_for(cls):
    fn = _JITTED_SAMPLE_LOWRANK_CACHE.get(cls)
    if fn is None:

        def sample(key, array_params, static_items, num_solutions, rank, basis=None):
            params = dict(array_params)
            params.update(dict(static_items))
            return cls._sample_lowrank(key, params, num_solutions, rank, basis)

        # basis=None and basis=<array> trace as distinct jit signatures
        fn = jax.jit(sample, static_argnames=("static_items", "num_solutions", "rank"))
        _JITTED_SAMPLE_LOWRANK_CACHE[cls] = fn
    return fn


def _jitted_grads_for(cls):
    # keyed on the fused-rank flag as well as the class: rank() reads
    # EVOTORCH_TPU_FUSED_RANK at trace time (tools/ranking.py), so a cache
    # hit after the env var changed would silently keep the stale executable
    import os

    cache_key = (cls, os.environ.get("EVOTORCH_TPU_FUSED_RANK", "auto"))
    fn = _JITTED_GRADS_CACHE.get(cache_key)
    if fn is None:

        def grads(array_params, samples, fitnesses, static_items, ranking_method, higher_is_better):
            params = dict(array_params)
            params.update(dict(static_items))
            weights = rank(fitnesses, ranking_method, higher_is_better=higher_is_better)
            return cls._compute_gradients(params, samples, weights, ranking_method)

        fn = jax.jit(
            grads, static_argnames=("static_items", "ranking_method", "higher_is_better")
        )
        _JITTED_GRADS_CACHE[cache_key] = fn
    return fn


class Distribution(TensorMakerMixin, Serializable, RecursivePrintable):
    """Base class for search distributions (reference ``distributions.py:40``)."""

    MANDATORY_PARAMETERS: set = set()
    OPTIONAL_PARAMETERS: set = set()
    PARAMETER_NDIMS: dict = {}
    #: antithetic distributions require an even sample count per draw; the
    #: sharded grad estimator uses this to round shard-local popsizes
    SAMPLES_MUST_BE_EVEN: bool = False

    functional_sample: Optional[Callable] = None

    def __init__(
        self,
        *,
        solution_length: int,
        parameters: dict,
        dtype=None,
        seed: Optional[int] = None,
    ):
        self.solution_length = int(solution_length)
        self.dtype = to_jax_dtype(dtype) if dtype is not None else jnp.float32
        self._parameters = {}
        for k, v in parameters.items():
            if (k not in self.MANDATORY_PARAMETERS) and (k not in self.OPTIONAL_PARAMETERS):
                raise ValueError(f"{type(self).__name__} got an unrecognized parameter: {k!r}")
            if isinstance(v, (str, type(None))):
                self._parameters[k] = v
            elif isinstance(v, (int, float)) and k in ("parenthood_ratio",):
                self._parameters[k] = float(v)
            else:
                self._parameters[k] = jnp.asarray(v, dtype=self.dtype)
        for k in self.MANDATORY_PARAMETERS:
            if k not in self._parameters:
                raise ValueError(f"{type(self).__name__} is missing mandatory parameter {k!r}")
        self._rng_key = jax.random.key(0 if seed is None else seed)

    # -- PRNG plumbing ------------------------------------------------------
    def manual_seed(self, seed: int):
        self._rng_key = jax.random.key(int(seed))

    def next_rng_key(self):
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    # -- parameters ----------------------------------------------------------
    @property
    def parameters(self) -> dict:
        return self._parameters

    def modified_copy(self, *, dtype=None, **overrides) -> "Distribution":
        """Copy with some parameters replaced (reference ``distributions.py:328``)."""
        params = dict(self._parameters)
        params.update(overrides)
        result = type(self)(
            parameters=params,
            solution_length=self.solution_length,
            dtype=dtype if dtype is not None else self.dtype,
        )
        result._rng_key = self._rng_key
        return result

    # -- sampling ------------------------------------------------------------
    def sample(self, num_solutions: int, *, key=None) -> jnp.ndarray:
        """Draw ``num_solutions`` samples (reference ``distributions.py:155-216``).
        ``key`` is an explicit JAX PRNG key; when omitted, the distribution's
        internal key state advances (stateful convenience)."""
        if key is None:
            key = self.next_rng_key()
        arrays, static = _split_params(self._parameters)
        return _jitted_sample_for(type(self))(key, arrays, static, int(num_solutions))

    @classmethod
    def _sample(cls, key, parameters: dict, num_solutions: int) -> jnp.ndarray:
        raise NotImplementedError

    # -- gradients -----------------------------------------------------------
    def compute_gradients(
        self,
        samples: jnp.ndarray,
        fitnesses: jnp.ndarray,
        *,
        objective_sense: str,
        ranking_method: str = "raw",
    ) -> dict:
        """Rank fitnesses then delegate (reference ``distributions.py:236-299``)."""
        if objective_sense not in ("min", "max"):
            raise ValueError(f"objective_sense must be 'min' or 'max', got {objective_sense!r}")
        higher_is_better = objective_sense == "max"
        arrays, static = _split_params(self._parameters)
        if not is_factored(samples):
            samples = jnp.asarray(samples)  # structured samples are pytrees already
        return _jitted_grads_for(type(self))(
            arrays, samples, jnp.asarray(fitnesses), static, ranking_method, higher_is_better
        )

    @classmethod
    def _compute_gradients(cls, parameters: dict, samples, weights, ranking_used) -> dict:
        raise NotImplementedError

    # -- updates -------------------------------------------------------------
    def _follow_gradient(
        self,
        param_name: str,
        grad: jnp.ndarray,
        *,
        learning_rates: Optional[dict] = None,
        optimizers: Optional[dict] = None,
    ) -> jnp.ndarray:
        """Learning-rate step or optimizer ``ascent`` (reference
        ``distributions.py:372-392``)."""
        if optimizers is not None and param_name in optimizers:
            return optimizers[param_name].ascent(grad)
        if learning_rates is not None and param_name in learning_rates:
            return jnp.asarray(learning_rates[param_name], dtype=grad.dtype) * grad
        return grad

    def update_parameters(
        self,
        gradients: dict,
        *,
        learning_rates: Optional[dict] = None,
        optimizers: Optional[dict] = None,
    ) -> "Distribution":
        raise NotImplementedError

    # -- misc ----------------------------------------------------------------
    def relative_entropy(self, other: "Distribution") -> float:
        raise NotImplementedError(
            f"KL divergence is not defined for {type(self).__name__}"
        )

    def _printable_items(self):
        return {"solution_length": self.solution_length, "parameters": self._parameters}


def _zero_center_weights(weights: jnp.ndarray, ranking_used: Optional[str]) -> jnp.ndarray:
    """Weights must be 0-centered for the score-function estimators unless the
    ranking already guarantees it (reference ``distributions.py:560-563``)."""
    if ranking_used not in ("centered", "normalized"):
        weights = weights - jnp.mean(weights)
    return weights


def _divide_grad(parameters: dict, param_name: str, grad, weights):
    """Configurable gradient divisor (reference ``distributions.py:517-536``)."""
    option = f"divide_{param_name}_grad_by"
    div_by_what = parameters.get(option, None)
    if div_by_what is None:
        return grad
    if div_by_what == "num_solutions":
        return grad / weights.shape[0]
    if div_by_what == "num_directions":
        return grad / (weights.shape[0] // 2)
    if div_by_what == "total_weight":
        return grad / jnp.sum(jnp.abs(weights))
    if div_by_what == "weight_stdev":
        return grad / jnp.std(weights, ddof=1)
    raise ValueError(f"The parameter {option} has an unrecognized value: {div_by_what}")


class SeparableGaussian(Distribution):
    """Separable multivariate Gaussian, as used by PGPE (non-symmetric) and —
    with ``parenthood_ratio`` — CEM (reference ``distributions.py:413-613``)."""

    MANDATORY_PARAMETERS = {"mu", "sigma"}
    OPTIONAL_PARAMETERS = {"divide_mu_grad_by", "divide_sigma_grad_by", "parenthood_ratio"}
    PARAMETER_NDIMS = {"mu": 1, "sigma": 1}

    def __init__(self, parameters: dict, *, solution_length: Optional[int] = None, dtype=None, seed=None):
        mu = jnp.asarray(parameters["mu"])
        if solution_length is None:
            solution_length = mu.shape[-1]
        elif solution_length != mu.shape[-1]:
            raise ValueError(
                f"solution_length={solution_length} does not match len(mu)={mu.shape[-1]}"
            )
        sigma = jnp.asarray(parameters["sigma"])
        if sigma.shape[-1] != mu.shape[-1]:
            raise ValueError(
                f"mu and sigma have mismatching lengths: {mu.shape[-1]} vs {sigma.shape[-1]}"
            )
        super().__init__(solution_length=solution_length, parameters=parameters, dtype=dtype, seed=seed)

    @property
    def mu(self) -> jnp.ndarray:
        return self._parameters["mu"]

    @property
    def sigma(self) -> jnp.ndarray:
        return self._parameters["sigma"]

    @classmethod
    def _sample(cls, key, parameters, num_solutions):
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        eps = jax.random.normal(key, (num_solutions, mu.shape[-1]), dtype=mu.dtype)
        return mu + sigma * eps

    @classmethod
    def _compute_gradients_via_parenthood_ratio(cls, parameters, samples, weights) -> dict:
        """CEM-style elite update (reference ``distributions.py:538-546``):
        gradient = (elite mean/std) - current (mu/sigma). Uses top-k by weight,
        fixed elite count, so it stays jit-friendly."""
        num_samples = samples.shape[0]
        num_elites = int(num_samples * float(parameters["parenthood_ratio"]))
        _, elite_indices = jax.lax.top_k(weights, num_elites)
        elites = samples[elite_indices, :]
        return {
            "mu": jnp.mean(elites, axis=0) - parameters["mu"],
            "sigma": jnp.std(elites, axis=0, ddof=1) - parameters["sigma"],
        }

    @classmethod
    def _compute_gradients(cls, parameters, samples, weights, ranking_used) -> dict:
        if "parenthood_ratio" in parameters:
            return cls._compute_gradients_via_parenthood_ratio(parameters, samples, weights)
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        scaled_noises = samples - mu
        weights = _zero_center_weights(weights, ranking_used)
        mu_grad = _divide_grad(parameters, "mu", weights @ scaled_noises, weights)
        sigma_grad = _divide_grad(
            parameters,
            "sigma",
            weights @ ((scaled_noises**2 - sigma**2) / sigma),
            weights,
        )
        return {"mu": mu_grad, "sigma": sigma_grad}

    def update_parameters(self, gradients, *, learning_rates=None, optimizers=None):
        new_mu = self.mu + self._follow_gradient(
            "mu", gradients["mu"], learning_rates=learning_rates, optimizers=optimizers
        )
        new_sigma = self.sigma + self._follow_gradient(
            "sigma", gradients["sigma"], learning_rates=learning_rates, optimizers=optimizers
        )
        return self.modified_copy(mu=new_mu, sigma=new_sigma)

    def relative_entropy(self, other: "SeparableGaussian") -> float:
        """KL(self || other) for diagonal Gaussians (reference
        ``distributions.py:598-613``)."""
        cov0 = self.sigma**2
        cov1 = other.sigma**2
        mu_delta = other.mu - self.mu
        trace_cov = jnp.sum(cov0 / cov1)
        k = self.solution_length
        scaled_mu = jnp.sum(mu_delta**2 / cov1)
        log_det = jnp.sum(jnp.log(cov1)) - jnp.sum(jnp.log(cov0))
        return float(0.5 * (trace_cov - k + scaled_mu + log_det))


def _make_class_functional_sample(cls):
    """Key-splitting batched sampler: batch dims on the parameters produce
    *independent* noise per batch lane (keys are split in
    make_functional_sampler, unlike a naive vmap with a broadcast key)."""

    def functional_sample(num_solutions: int, parameters: dict, *, key):
        return make_functional_sampler(cls)(key, int(num_solutions), parameters)

    return functional_sample


def _use_fused_sampling() -> bool:
    """Opt-in dispatch of antithetic sampling to the fused on-chip-PRNG
    kernel (``ops/sampling.py``). Off by default: the kernel draws from a
    different random stream than XLA's threefry, so enabling it changes
    sampled values (not just speed); set ``EVOTORCH_TPU_FUSED_SAMPLING=1``
    after micro-benching (``bench_ops.py``) shows a win on your shapes.
    TPU only — the on-chip PRNG primitives have no lowering elsewhere, so on
    other backends the flag warns once and the XLA path runs.

    Read at trace time, like ``EVOTORCH_TPU_FUSED_RANK``: the OO samplers key
    their jit cache on the flag's value, so toggling the env var takes effect
    on the next ``sample()``; user-jitted functional samplers bake the value
    at their own first trace."""
    import os

    if os.environ.get("EVOTORCH_TPU_FUSED_SAMPLING", "0") != "1":
        return False
    if jax.default_backend() == "tpu":
        return True
    import warnings

    warnings.warn(
        "EVOTORCH_TPU_FUSED_SAMPLING=1 ignored: the fused sampling kernel's "
        f"on-chip PRNG only lowers on TPU (current backend: "
        f"{jax.default_backend()}); using the XLA sampler",
        stacklevel=3,
    )
    return False


class SymmetricSeparableGaussian(SeparableGaussian):
    """Antithetic separable Gaussian, the PGPE default
    (reference ``distributions.py:616-773``)."""

    SAMPLES_MUST_BE_EVEN = True

    @classmethod
    def _sample(cls, key, parameters, num_solutions):
        if num_solutions % 2 != 0:
            raise ValueError(
                f"Number of solutions sampled from {cls.__name__} must be even, got {num_solutions}"
            )
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        if _use_fused_sampling():
            # opt-in fused TPU kernel (ops/sampling.py): on-chip PRNG +
            # scale/antithetic blocks in VMEM. Distribution-equivalent but a
            # DIFFERENT random stream than the XLA threefry path — hence
            # opt-in via EVOTORCH_TPU_FUSED_SAMPLING=1, never a silent swap
            from .ops.sampling import sample_symmetric_gaussian

            return sample_symmetric_gaussian(
                key, mu, sigma, num_solutions, use_pallas=True
            )
        num_directions = num_solutions // 2
        eps = jax.random.normal(key, (num_directions, mu.shape[-1]), dtype=mu.dtype) * sigma
        # interleaved [mu+e0, mu-e0, mu+e1, mu-e1, ...]
        pairs = jnp.stack([mu + eps, mu - eps], axis=1)
        return pairs.reshape(num_solutions, mu.shape[-1])

    @classmethod
    def _compute_gradients(cls, parameters, samples, weights, ranking_used) -> dict:
        if is_factored(samples):
            # both factored forms expose the same center/basis/coeffs algebra
            # (tools.lowrank.FACTORED_BATCH_TYPES); the gradient math below
            # reads only .basis/.coeffs, so it covers trunk-delta batches too
            return cls._compute_gradients_lowrank(parameters, samples, weights, ranking_used)
        if "parenthood_ratio" in parameters:
            return cls._compute_gradients_via_parenthood_ratio(parameters, samples, weights)
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        weights = _zero_center_weights(weights, ranking_used)
        scaled_noises = samples[0::2] - mu
        fdplus = weights[0::2]
        fdminus = weights[1::2]
        mu_grad = _divide_grad(
            parameters, "mu", ((fdplus - fdminus) / 2) @ scaled_noises, weights
        )
        sigma_grad = _divide_grad(
            parameters,
            "sigma",
            ((fdplus + fdminus) / 2) @ ((scaled_noises**2 - sigma**2) / sigma),
            weights,
        )
        return {"mu": mu_grad, "sigma": sigma_grad}

    # ------------------- factored (low-rank) population mode -----------------
    # The MXU path for wide policies (tools/lowrank.py): the population is
    # theta_i = mu + (sigma * B) z_i with a shared per-generation basis
    # B (L, rank) and per-lane coefficients z_i — and both the sampling and
    # the gradient estimate factor through the basis, so the dense (N, L)
    # population matrix is never materialized. With B entries ~ N(0, 1/rank)
    # the per-coordinate marginal variance of a perturbation is sigma^2 in
    # expectation over the basis (for a fixed per-generation basis the
    # per-coordinate variance fluctuates with relative stddev ~sqrt(2/rank),
    # so sigma-adaptation calibration is noisier at small rank).
    #
    # No reference counterpart (the reference evaluates dense populations
    # only); the math below is this class's dense symmetric gradient
    # rewritten in factored form:
    #   scaled_noises = B_eff Z^T            (never built)
    #   mu_grad    = B_eff @ (((f+ - f-)/2) @ Z)
    #   sigma_grad = (rowquad(B_eff, Z^T diag((f+ + f-)/2) Z)
    #                 - sum((f+ + f-)/2) sigma^2) / sigma
    # which equal the dense formulas exactly (tested in test_lowrank.py).

    @classmethod
    def _sample_lowrank(cls, key, parameters, num_solutions, rank, basis=None):
        """Draw a ``LowRankParamsBatch``: antithetic coefficient pairs
        interleaved ``[+z0, -z0, +z1, -z1, ...]`` (the dense sampler's
        direction layout above), sigma folded into the basis.

        With ``basis`` given, only fresh coefficients are drawn against that
        (already sigma-folded) basis — the shared-per-generation-basis mode
        that makes factored batches concatenable, so the adaptive-popsize
        loop (``num_interactions``) can keep sampling rounds within one
        generation's subspace (reference ``core.py:3239-3282`` concatenates
        dense rounds the same way)."""
        if num_solutions % 2 != 0:
            raise ValueError(
                f"Number of solutions sampled from {cls.__name__} must be even,"
                f" got {num_solutions}"
            )
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        rank = int(rank)
        key_basis, key_coeffs = jax.random.split(key)
        if basis is None:
            basis = jax.random.normal(
                key_basis, (mu.shape[-1], rank), dtype=mu.dtype
            ) / jnp.sqrt(jnp.asarray(float(rank), mu.dtype))
            basis = sigma[..., None] * basis  # sigma folded in: delta = basis @ z
        elif basis.shape[-1] != rank:
            # fail fast: a rank/basis mismatch would otherwise surface as an
            # opaque dot_general shape error deep inside a jitted forward
            raise ValueError(
                f"basis has rank {basis.shape[-1]} but rank={rank} was requested"
            )
        num_directions = num_solutions // 2
        z = jax.random.normal(key_coeffs, (num_directions, rank), dtype=mu.dtype)
        coeffs = jnp.stack([z, -z], axis=1).reshape(num_solutions, rank)
        return LowRankParamsBatch(center=mu, basis=basis, coeffs=coeffs)

    def sample_lowrank(
        self, num_solutions: int, rank: int, *, key=None, basis=None
    ) -> LowRankParamsBatch:
        """Stateful-API counterpart of :meth:`_sample_lowrank` (jitted per
        class like :meth:`sample`). ``basis`` reuses an existing sigma-folded
        basis (shared-per-generation-basis mode)."""
        if key is None:
            key = self.next_rng_key()
        arrays, static = _split_params(self._parameters)
        out = _jitted_sample_lowrank_for(type(self))(
            key, arrays, static, int(num_solutions), int(rank), basis
        )
        # the jitted call returns fresh output buffers even for passed-through
        # arrays; restoring the original objects keeps SolutionBatch.cat's
        # shared-basis check on the `is` fast path (center is always a mu
        # passthrough; basis only when the caller supplied one)
        out = out._replace(center=self._parameters["mu"])
        if basis is not None:
            out = out._replace(basis=basis)
        return out

    @classmethod
    def _compute_gradients_lowrank(cls, parameters, samples: LowRankParamsBatch, weights, ranking_used) -> dict:
        """The dense symmetric gradients computed in O(L * rank) from the
        factored population — numerically identical to running
        ``_compute_gradients`` on ``samples.materialize()``."""
        sigma = parameters["sigma"]
        weights = _zero_center_weights(weights, ranking_used)
        z = samples.coeffs[0::2]  # (D, rank): the +z of each antithetic pair
        basis = samples.basis  # sigma-folded effective basis (L, rank)
        fdplus = weights[0::2]
        fdminus = weights[1::2]
        mu_grad = _divide_grad(
            parameters, "mu", basis @ (((fdplus - fdminus) / 2) @ z), weights
        )
        w_s = (fdplus + fdminus) / 2
        m = z.T @ (w_s[:, None] * z)  # (rank, rank)
        rowquad = jnp.einsum("lm,mn,ln->l", basis, m, basis)
        sigma_grad = _divide_grad(
            parameters, "sigma", (rowquad - jnp.sum(w_s) * sigma**2) / sigma, weights
        )
        return {"mu": mu_grad, "sigma": sigma_grad}

    @classmethod
    def _sample_trunk_delta(
        cls, key, parameters, num_solutions, rank, factors, basis
    ) -> TrunkDeltaParamsBatch:
        """Draw a ``TrunkDeltaParamsBatch`` against an externally-structured
        (factors, effective-basis) pair — the shared-trunk policy form
        (``neuroevolution/net/lowrank.py``'s ``sample_trunk_delta_factors``
        draws the pair; the structure is policy-shaped, so it cannot be
        drawn here). The antithetic coefficient layout is exactly
        :meth:`_sample_lowrank`'s, so gradients, concatenation and the
        guardrail see an ordinary factored batch."""
        lr = cls._sample_lowrank(key, parameters, num_solutions, rank, basis=basis)
        return TrunkDeltaParamsBatch(
            center=lr.center, basis=lr.basis, coeffs=lr.coeffs, factors=factors
        )





class ExpSeparableGaussian(SeparableGaussian):
    """Exponential separable Gaussian, as used by SNES
    (reference ``distributions.py:776-810``)."""

    MANDATORY_PARAMETERS = {"mu", "sigma"}
    OPTIONAL_PARAMETERS: set = set()
    PARAMETER_NDIMS = {"mu": 1, "sigma": 1}

    @classmethod
    def _compute_gradients(cls, parameters, samples, weights, ranking_used) -> dict:
        if ranking_used != "nes":
            weights = weights / jnp.sum(jnp.abs(weights))
        mu = parameters["mu"]
        sigma = parameters["sigma"]
        scaled_noises = samples - mu
        raw_noises = scaled_noises / sigma
        mu_grad = weights @ scaled_noises
        sigma_grad = weights @ (raw_noises**2 - 1)
        return {"mu": mu_grad, "sigma": sigma_grad}

    def update_parameters(self, gradients, *, learning_rates=None, optimizers=None):
        new_mu = self.mu + self._follow_gradient(
            "mu", gradients["mu"], learning_rates=learning_rates, optimizers=optimizers
        )
        new_sigma = self.sigma * jnp.exp(
            0.5
            * self._follow_gradient(
                "sigma", gradients["sigma"], learning_rates=learning_rates, optimizers=optimizers
            )
        )
        return self.modified_copy(mu=new_mu, sigma=new_sigma)





class ExpGaussian(Distribution):
    """Exponential full-covariance Gaussian, as used by XNES
    (reference ``distributions.py:813-1016``). ``sigma`` is ``A``, the square
    root of the covariance; ``sigma_inv`` is tracked independently for
    numerical stability."""

    MANDATORY_PARAMETERS = {"mu", "sigma"}
    OPTIONAL_PARAMETERS = {"sigma_inv"}
    PARAMETER_NDIMS = {"mu": 1, "sigma": 2, "sigma_inv": 2}

    def __init__(self, parameters: dict, *, solution_length: Optional[int] = None, dtype=None, seed=None):
        parameters = dict(parameters)
        mu = jnp.asarray(parameters["mu"])
        sigma = jnp.asarray(parameters["sigma"])
        if sigma.ndim == 1:
            sigma = jnp.diag(sigma)
        parameters["sigma"] = sigma
        if "sigma_inv" not in parameters:
            parameters["sigma_inv"] = jnp.linalg.inv(sigma)
        if solution_length is None:
            solution_length = mu.shape[-1]
        elif solution_length != mu.shape[-1]:
            raise ValueError(
                f"solution_length={solution_length} does not match len(mu)={mu.shape[-1]}"
            )
        if sigma.shape[-1] != mu.shape[-1]:
            raise ValueError(
                f"mu and sigma have mismatching lengths: {mu.shape[-1]} vs {sigma.shape[-1]}"
            )
        super().__init__(solution_length=solution_length, parameters=parameters, dtype=dtype, seed=seed)

    @property
    def mu(self) -> jnp.ndarray:
        return self._parameters["mu"]

    @property
    def sigma(self) -> jnp.ndarray:
        return self._parameters["sigma"]

    @property
    def A(self) -> jnp.ndarray:
        return self.sigma

    @property
    def sigma_inv(self) -> jnp.ndarray:
        return self._parameters["sigma_inv"]

    @property
    def A_inv(self) -> jnp.ndarray:
        return self.sigma_inv

    @property
    def cov(self) -> jnp.ndarray:
        return self.sigma.T @ self.sigma

    @classmethod
    def _to_global(cls, parameters, z):
        # x = mu + A z  (batched: z @ A^T) — reference distributions.py:928
        return parameters["mu"] + z @ parameters["sigma"].T

    @classmethod
    def _to_local(cls, parameters, x):
        # z = A_inv (x - mu) — reference distributions.py:940
        return (x - parameters["mu"]) @ parameters["sigma_inv"].T

    def to_global_coordinates(self, z: jnp.ndarray) -> jnp.ndarray:
        return self._to_global(self._parameters, z)

    def to_local_coordinates(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._to_local(self._parameters, x)

    @classmethod
    def _sample(cls, key, parameters, num_solutions):
        mu = parameters["mu"]
        z = jax.random.normal(key, (num_solutions, mu.shape[-1]), dtype=mu.dtype)
        return cls._to_global(parameters, z)

    @classmethod
    def _compute_gradients(cls, parameters, samples, weights, ranking_used) -> dict:
        z = cls._to_local(parameters, samples)
        weights = _zero_center_weights(weights, ranking_used)
        d_grad = weights @ z
        eye = jnp.eye(z.shape[-1], dtype=z.dtype)
        outer = z[:, :, None] * z[:, None, :]
        M_grad = jnp.sum(weights[:, None, None] * (outer - eye), axis=0)
        return {"d": d_grad, "M": M_grad}

    def update_parameters(self, gradients, *, learning_rates=None, optimizers=None):
        learning_rates = dict(learning_rates) if learning_rates is not None else {}
        if "d" not in learning_rates and "mu" in learning_rates:
            learning_rates["d"] = learning_rates["mu"]
        if "M" not in learning_rates and "sigma" in learning_rates:
            learning_rates["M"] = learning_rates["sigma"]
        update_d = self._follow_gradient("d", gradients["d"], learning_rates=learning_rates, optimizers=optimizers)
        update_M = self._follow_gradient("M", gradients["M"], learning_rates=learning_rates, optimizers=optimizers)
        new_mu = self.mu + self.A @ update_d
        expm = jax.scipy.linalg.expm
        new_A = self.A @ expm(0.5 * update_M)
        new_A_inv = expm(-0.5 * update_M) @ self.A_inv
        return self.modified_copy(mu=new_mu, sigma=new_A, sigma_inv=new_A_inv)





# ---------------------------------------------------------------------------
# Functional factories (reference distributions.py:1023-1623)
# ---------------------------------------------------------------------------


def make_functional_sampler(distribution_class: Type[Distribution]) -> Callable:
    """Return a stateless, vmap-batchable sampler
    ``f(key, num_solutions, parameters) -> samples``
    (reference ``distributions.py:1023-1193`` ``FunctionalSampler``).

    Batch dims on the parameter arrays produce batched sample populations; the
    key is split across the batch automatically."""

    param_ndims = distribution_class.PARAMETER_NDIMS

    def sampler(key, num_solutions: int, parameters: dict) -> jnp.ndarray:
        # normalized ONCE on the host side: num_solutions must never look
        # like a traced value inside the vmapped `one` below (graftlint
        # `host-sync` — int() under trace is a concretization hazard)
        num_solutions = int(num_solutions)
        array_params = {
            k: jnp.asarray(v)
            for k, v in parameters.items()
            if k in param_ndims and not isinstance(v, str)
        }
        other_params = {k: v for k, v in parameters.items() if k not in array_params}
        batch_shape = ()
        for k, v in array_params.items():
            nd = param_ndims[k]
            batch_shape = jnp.broadcast_shapes(batch_shape, v.shape[: v.ndim - nd])
        if batch_shape == ():
            return distribution_class._sample(key, {**array_params, **other_params}, num_solutions)

        import math as _math

        bsize = _math.prod(batch_shape)
        flat_params = {}
        for k, v in array_params.items():
            nd = param_ndims[k]
            core = v.shape[v.ndim - nd :]
            flat_params[k] = jnp.broadcast_to(v, batch_shape + core).reshape((bsize,) + core)
        keys = jax.random.split(key, bsize)

        def one(key, params):
            return distribution_class._sample(key, {**params, **other_params}, num_solutions)

        out = jax.vmap(one)(keys, flat_params)
        return out.reshape(batch_shape + out.shape[1:])

    sampler.__name__ = f"functional_sampler_of_{distribution_class.__name__}"
    return sampler


def make_functional_grad_estimator(
    distribution_class: Type[Distribution],
    *,
    function: Optional[Callable] = None,
    objective_sense: str,
    ranking_method: str = "raw",
    return_samples: bool = False,
    return_fitnesses: bool = False,
) -> Callable:
    """Return a stateless gradient estimator
    (reference ``distributions.py:1196-1623`` ``FunctionalGradEstimator``).

    Without ``function``: ``g(samples, fitnesses, parameters) -> grads``.
    With a bound fitness ``function``: ``g(key, num_solutions, parameters,
    *fn_args) -> grads`` (samples internally, evaluates, estimates). Extra
    outputs are appended when ``return_samples``/``return_fitnesses``."""

    higher_is_better = {"max": True, "min": False}[objective_sense]
    sampler = make_functional_sampler(distribution_class)
    param_ndims = distribution_class.PARAMETER_NDIMS

    def _estimate(parameters: dict, samples, fitnesses) -> dict:
        array_params = {
            k: jnp.asarray(v)
            for k, v in parameters.items()
            if k in param_ndims and not isinstance(v, str)
        }
        other_params = {k: v for k, v in parameters.items() if k not in array_params}
        batch_shape = ()
        for k, v in array_params.items():
            nd = param_ndims[k]
            batch_shape = jnp.broadcast_shapes(batch_shape, v.shape[: v.ndim - nd])
        batch_shape = jnp.broadcast_shapes(batch_shape, jnp.asarray(fitnesses).shape[:-1])

        def one(params, samples, fitnesses):
            weights = rank(fitnesses, ranking_method, higher_is_better=higher_is_better)
            return distribution_class._compute_gradients(
                {**params, **other_params}, samples, weights, ranking_method
            )

        if batch_shape == ():
            return one(array_params, jnp.asarray(samples), jnp.asarray(fitnesses))

        import math as _math

        bsize = _math.prod(batch_shape)
        flat_params = {}
        for k, v in array_params.items():
            nd = param_ndims[k]
            core = v.shape[v.ndim - nd :]
            flat_params[k] = jnp.broadcast_to(v, batch_shape + core).reshape((bsize,) + core)
        samples = jnp.asarray(samples)
        fitnesses = jnp.asarray(fitnesses)
        samples = jnp.broadcast_to(samples, batch_shape + samples.shape[-2:]).reshape(
            (bsize,) + samples.shape[-2:]
        )
        fitnesses = jnp.broadcast_to(fitnesses, batch_shape + fitnesses.shape[-1:]).reshape(
            (bsize,) + fitnesses.shape[-1:]
        )
        out = jax.vmap(one)(flat_params, samples, fitnesses)
        return jax.tree_util.tree_map(lambda leaf: leaf.reshape(batch_shape + leaf.shape[1:]), out)

    if function is None:

        def estimator(samples, fitnesses, parameters: dict):
            return _estimate(parameters, samples, fitnesses)

    else:

        def estimator(key, num_solutions: int, parameters: dict, *fn_args, **fn_kwargs):
            samples = sampler(key, num_solutions, parameters)
            fitnesses = function(samples, *fn_args, **fn_kwargs)
            grads = _estimate(parameters, samples, fitnesses)
            extras = []
            if return_samples:
                extras.append(samples)
            if return_fitnesses:
                extras.append(fitnesses)
            if extras:
                return (grads, *extras)
            return grads

    estimator.__name__ = f"functional_grad_estimator_of_{distribution_class.__name__}"
    return estimator


for _cls in (SeparableGaussian, SymmetricSeparableGaussian, ExpSeparableGaussian, ExpGaussian):
    _cls.functional_sample = staticmethod(_make_class_functional_sample(_cls))
del _cls
