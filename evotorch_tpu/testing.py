"""Test-assertion utilities (L8).

Parity: reference ``testing.py`` (273 LoC) — ``assert_allclose``
(``testing.py:100``), ``assert_almost_between`` (``testing.py:157``),
``assert_dtype_matches`` (``testing.py:201``), ``assert_shape_matches``
(``testing.py:231``), ``assert_eachclose`` (``testing.py:254``). All helpers
accept jax arrays, numpy arrays, Solutions and SolutionBatches.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Union

import numpy as np

__all__ = [
    "TestingError",
    "assert_allclose",
    "assert_almost_between",
    "assert_dtype_matches",
    "assert_shape_matches",
    "assert_eachclose",
]


class TestingError(AssertionError):
    """Raised when a testing assertion fails (reference ``testing.py:31``)."""


def _to_numpy(x: Any) -> np.ndarray:
    if hasattr(x, "evals") and hasattr(x, "values"):
        # Solution / SolutionBatch: compare by decision values
        x = x.values
    return np.asarray(x)


def assert_allclose(
    actual: Any,
    desired: Any,
    *,
    rtol: Optional[float] = None,
    atol: Optional[float] = None,
    equal_nan: bool = True,
):
    """Elementwise closeness with mandatory tolerance (reference
    ``testing.py:100``: at least one of rtol/atol is required)."""
    if rtol is None and atol is None:
        raise ValueError("Provide at least one of `rtol` / `atol`")
    a = _to_numpy(actual)
    d = _to_numpy(desired)
    kwargs = {}
    if rtol is not None:
        kwargs["rtol"] = rtol
        if atol is None:
            kwargs["atol"] = 0.0
    if atol is not None:
        kwargs["atol"] = atol
        if rtol is None:
            kwargs["rtol"] = 0.0
    try:
        np.testing.assert_allclose(a, d, equal_nan=equal_nan, **kwargs)
    except AssertionError as e:
        raise TestingError(str(e)) from None


def assert_almost_between(
    x: Any,
    lb: Union[float, Any],
    ub: Union[float, Any],
    *,
    atol: Optional[float] = None,
):
    """Assert all elements are (almost) within [lb, ub]
    (reference ``testing.py:157``)."""
    arr = _to_numpy(x)
    lb = np.asarray(lb)
    ub = np.asarray(ub)
    tolerance = 0.0 if atol is None else float(atol)
    below = arr < (lb - tolerance)
    above = arr > (ub + tolerance)
    if bool(np.any(below)) or bool(np.any(above)):
        raise TestingError(
            f"Some elements are outside [{lb}, {ub}] (atol={atol}): "
            f"min={arr.min()}, max={arr.max()}"
        )


def assert_dtype_matches(x: Any, dtype: Any):
    """Assert dtype equality; ``dtype`` may be a dtype-like or "float"/"int"/
    "bool" kind strings (reference ``testing.py:201``)."""
    arr = _to_numpy(x)
    if isinstance(dtype, str) and dtype in ("float", "int", "bool"):
        kinds = {"float": "f", "int": "iu", "bool": "b"}[dtype]
        if arr.dtype.kind not in kinds:
            raise TestingError(f"dtype kind mismatch: {arr.dtype} is not of kind {dtype}")
        return
    from .tools.misc import to_numpy_dtype

    expected = to_numpy_dtype(dtype)
    if np.dtype(arr.dtype) != expected:
        raise TestingError(f"dtype mismatch: {arr.dtype} != {expected}")


def assert_shape_matches(x: Any, shape: Union[int, Iterable]):
    """Assert shape equality; ``*`` entries match any size
    (reference ``testing.py:231``)."""
    arr = _to_numpy(x)
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(shape)
    if arr.ndim != len(shape):
        raise TestingError(f"shape mismatch: {arr.shape} vs {shape}")
    for actual_dim, expected_dim in zip(arr.shape, shape):
        if expected_dim in ("*", -1, None):
            continue
        if actual_dim != int(expected_dim):
            raise TestingError(f"shape mismatch: {arr.shape} vs {shape}")


def assert_eachclose(x: Any, value: Any, *, rtol: Optional[float] = None, atol: Optional[float] = None):
    """Assert every element is close to the scalar ``value``
    (reference ``testing.py:254``). The comparison promotes to float so an
    integer array is NOT considered close to a fractional target."""
    arr = _to_numpy(x)
    expected = np.full(arr.shape, value, dtype=np.result_type(arr.dtype, np.asarray(value).dtype, np.float32))
    assert_allclose(arr.astype(expected.dtype), expected, rtol=rtol, atol=atol)
