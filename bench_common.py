"""Shared scaffolding for bench.py and bench_multichip.py: the TPU health
probe / CPU fallback dance, BENCH_* env-var parsing, and the policy builder —
one place, so the two benchmarks cannot silently diverge."""

import json
import os
import subprocess
import sys


def tpu_healthy() -> bool:
    """Probe backend init in a subprocess: the axon plugin can hang forever
    when its tunnel is unhealthy, which must not stall the benchmark driver."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            timeout=120,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def setup_backend() -> bool:
    """Pick TPU when the tunnel is healthy, else an 8-virtual-device CPU.
    Must run before jax's first device use. Returns use_cpu."""
    requested_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    use_cpu = requested_cpu or not tpu_healthy()
    if use_cpu:
        if not requested_cpu:
            print("TPU backend unhealthy; falling back to CPU", file=sys.stderr)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if use_cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        # the subprocess probe above can race a tunnel that dies between the
        # probe and THIS process's first backend use — which would then hang
        # forever. The watchdog turns that hang into an actionable error
        # (EVOTORCH_DEVICE_TIMEOUT deadline; docs/resilience.md).
        from evotorch_tpu.resilience import probe_devices

        probe_devices()
    return use_cpu


def bench_config(use_cpu: bool, *, cpu_episode_length: int = 100) -> dict:
    """Parse the BENCH_* knobs (on the CPU fallback, defaults shrink so the
    benchmark cannot stall the driver).

    The popsize / episode-length / hidden / env defaults are mirrored by
    the autotuner CLI (observability/autotune.py:_shape_from_args) — KEEP
    THEM IN SYNC: tuned-config cache hits require exact shape equality,
    so a drifted default silently downgrades every lookup to fallback."""
    import jax.numpy as jnp

    return {
        "popsize": int(os.environ.get("BENCH_POPSIZE", 1024 if use_cpu else 10_000)),
        "episode_length": int(
            os.environ.get(
                "BENCH_EPISODE_LENGTH", cpu_episode_length if use_cpu else 200
            )
        ),
        "generations": int(os.environ.get("BENCH_GENERATIONS", 3)),
        # opt-in bf16: changes the measured compute dtype, so the default
        # stays comparable with previously recorded f32 baselines
        "compute_dtype": (
            jnp.bfloat16 if os.environ.get("BENCH_BF16", "0") == "1" else None
        ),
        "eval_mode": os.environ.get("BENCH_EVAL_MODE", "budget"),
        # BENCH_TELEMETRY=0 compiles the accumulator-free rollout programs —
        # the A/B baseline proving the zero-sync telemetry costs nothing
        # (docs/observability.md); default on
        "telemetry": os.environ.get("BENCH_TELEMETRY", "1") != "0",
        # BENCH_HEALTH=0 compiles the health-plane-free (schema v3) eval
        # programs and drops the score_mean/score_std columns — both the
        # overhead A/B baseline for the search-health plane and the
        # byte-compat escape hatch (docs/observability.md "Search health");
        # default on (meaningful only with telemetry on)
        "health": os.environ.get("BENCH_HEALTH", "1") != "0",
        # BENCH_GROUPS=G (with telemetry on) assigns round-robin group ids
        # across the population and switches the telemetry wire to the
        # per-group (G, 14) matrix — the per-group accounting overhead A/B
        # (docs/observability.md "Per-group telemetry & SLOs"); 0/1 = off
        "num_groups": int(os.environ.get("BENCH_GROUPS", "0")),
        # BENCH_LEDGER=0 skips the program-ledger capture (one extra AOT
        # trace+compile per contract, outside every timed region) and with
        # it the compile_seconds / flops_per_step / peak_hbm_bytes /
        # model_efficiency columns — the output line is then byte-compatible
        # with pre-ledger rounds (docs/observability.md "Program ledger")
        "ledger": os.environ.get("BENCH_LEDGER", "1") != "0",
        # BENCH_LOWRANK=k: evaluate a low-rank-structured population of rank k
        # (the MXU path for wide policies, net/lowrank.py); 0 = dense
        "lowrank": int(os.environ.get("BENCH_LOWRANK", "0")),
        # BENCH_TRUNK_DELTA=1: evaluate a shared-trunk + per-lane
        # low-rank-delta population (docs/policies.md) — the per-lane forward
        # becomes ONE shared-weight GEMM over the whole popsize x obs batch
        # plus a cheap rank-k correction — and run the in-process interleaved
        # dense A/B (`trunk_delta_speedup` on the line). Rank / lane blocking
        # resolve like the refill schedule: explicit knobs override, else the
        # tuned-config cache's `policy` group, else rank 4 / no blocking.
        "trunk_delta": os.environ.get("BENCH_TRUNK_DELTA", "0") == "1",
        "trunk_rank": (
            int(os.environ["BENCH_TRUNK_RANK"])
            if "BENCH_TRUNK_RANK" in os.environ
            else None
        ),
        "trunk_block": (
            int(os.environ["BENCH_TRUNK_BLOCK"])
            if "BENCH_TRUNK_BLOCK" in os.environ
            else None
        ),
        # BENCH_SPAN=K fuses K generations per device dispatch
        # (parallel.make_training_span — ask→eval→tell scanned into ONE
        # donated program) and runs the in-process interleaved span-vs-
        # host-loop A/B (`span_speedup` on the line); "auto" consults the
        # tuned-config cache's `span` group (the `--group span` autotuner
        # winner, fallback 8). Unset = no span measurement, line
        # byte-compatible. episodes_compact is host-orchestrated and cannot
        # be fused; its span A/B runs on the budget contract instead
        # (`span_ab_mode` says which contract was measured).
        "span": os.environ.get("BENCH_SPAN"),
        "span_ab_repeats": int(os.environ.get("BENCH_SPAN_AB_REPEATS", "3")),
        # BENCH_SERVE=1 runs the multi-tenant serving A/B
        # (evotorch_tpu/serving, docs/serving.md): BENCH_SERVE_TENANTS
        # concurrent searches packed through ONE EvalServer's resident
        # episodes_refill program vs the same searches dispatched
        # sequentially standalone (`serve_speedup` on the line, plus
        # `serve_occupancy` and the per-tenant queue-wait quantiles).
        # Off by default, line byte-compatible.
        "serve": os.environ.get("BENCH_SERVE", "0") == "1",
        "serve_tenants": int(os.environ.get("BENCH_SERVE_TENANTS", "4")),
        "serve_ab_repeats": int(os.environ.get("BENCH_SERVE_AB_REPEATS", "3")),
        "env_name": os.environ.get("BENCH_ENV", "humanoid"),
        "env_kwargs": json.loads(os.environ.get("BENCH_ENV_ARGS", "{}")),
        # lane-compaction tuning (episodes_compact only): chunk size between
        # host width-decisions, and the width-menu floor — the knobs to sweep
        # on real hardware (BENCH_NOTES.md)
        "compact_chunk": int(os.environ.get("BENCH_COMPACT_CHUNK", "25")),
        "compact_chunk_explicit": "BENCH_COMPACT_CHUNK" in os.environ,
        "compact_min_width": (
            int(os.environ["BENCH_COMPACT_MINWIDTH"])
            if "BENCH_COMPACT_MINWIDTH" in os.environ
            else None
        ),
        # lane-refill tuning (episodes_refill only): the fixed lane width W
        # (default: engine picks ~work/8) and the refill period (refill every
        # k-th step; >1 amortizes the refill gather/reset at the cost of
        # finished lanes idling up to k-1 steps)
        "refill_width": (
            int(os.environ["BENCH_REFILL_WIDTH"])
            if "BENCH_REFILL_WIDTH" in os.environ
            else None
        ),
        "refill_period": int(os.environ.get("BENCH_REFILL_PERIOD", "1")),
        "refill_period_explicit": "BENCH_REFILL_PERIOD" in os.environ,
        # BENCH_TUNED=0 disables the tuned-config cache consult (and the
        # tuned_config_source column), keeping the line AND the measured
        # configs byte-compatible with pre-autotuner rounds. Default on:
        # with no explicit BENCH_REFILL_*/BENCH_COMPACT_* knobs the refill /
        # compaction schedules come from observability/tuned_configs.json
        # when this (env, popsize, machine) was tuned
        # (docs/observability.md "The autotuner").
        "tuned": os.environ.get("BENCH_TUNED", "1") != "0",
        # BENCH_COMPILE_CACHE=1 enables jax's persistent compilation cache
        # (observability/compilecache.py) and appends a `compile_cache`
        # block — hits/misses + cold/warm provenance — to the JSON line.
        # Default off: serialized executables are machine-local artifacts
        # and the default line stays byte-compatible.
        "compile_cache": os.environ.get("BENCH_COMPILE_CACHE", "0") == "1",
        # BENCH_BACKEND=mujoco: ALSO measure the real-MuJoCo host path (sync
        # chunked loop vs the pipelined refill scheduler) and append the
        # mj_* columns to the JSON line. Default off: the four bespoke-sim
        # contracts and their output stay byte-compatible.
        "mj_backend": os.environ.get("BENCH_BACKEND", "") == "mujoco",
        "mj_env": os.environ.get("BENCH_MJ_ENV", "Hopper-v5"),
        # 512 is past the refill crossover on this box (the drain tail — one
        # straggler's worth of low-occupancy rounds per eval — amortizes with
        # popsize; bench_curves/hopper_v5_pipeline_r7.json has 256 vs 512)
        "mj_popsize": int(os.environ.get("BENCH_MJ_POPSIZE", "512")),
        "mj_num_envs": int(os.environ.get("BENCH_MJ_NUM_ENVS", "32")),
        # the env's own -v5 horizon (1000): no artificial cap — straggler
        # episodes are exactly what separates the two schedulers
        "mj_episode_length": int(os.environ.get("BENCH_MJ_EPISODE_LENGTH", "1000")),
        # None = the scheduler's auto block split (2 when >1 core, else 1)
        "mj_blocks": (
            int(os.environ["BENCH_MJ_BLOCKS"]) if "BENCH_MJ_BLOCKS" in os.environ else None
        ),
        "mj_repeats": int(os.environ.get("BENCH_MJ_REPEATS", "1")),
    }


def _use_tuned_cache(cfg: dict, params) -> bool:
    # BENCH_ENV_ARGS mutates the env without changing its cache label, so a
    # tuned entry for the plain env would be wrong evidence — skip the
    # cache; likewise when the caller cannot say which policy size the
    # schedule would serve (params is part of the cache key)
    return cfg["tuned"] and not cfg["env_kwargs"] and params is not None


def _tuned_shape(cfg: dict, params, mesh_label: str = "none") -> dict:
    from evotorch_tpu.observability.timings import canonical_env_label, dtype_label

    return {
        "env": canonical_env_label(cfg["env_name"]),
        "popsize": cfg["popsize"],
        "episode_length": cfg["episode_length"],
        "num_episodes": 1,  # every bench contract evaluates one episode
        "params": params,
        "dtype": dtype_label(cfg["compute_dtype"]),
        # "none" for the single-device bench; bench_multichip looks up
        # under its own mesh label (a schedule tuned unsharded is not
        # evidence for a sharded layout — parallel.mesh.mesh_label)
        "mesh": mesh_label,
    }


def tuned_compact(cfg: dict, *, n_shards: int = 1, params=None, mesh_label: str = "none"):
    """Lane-compaction runner kwargs + ``tuned_config_source`` provenance:
    explicit ``BENCH_COMPACT_*`` knobs override; else (``BENCH_TUNED=1``,
    the default) the tuned-config cache entry for this
    (env, popsize, params, dtype, machine); else the runner defaults.
    ``params`` is the bench policy's parameter count (part of the cache
    key — a schedule tuned for one policy size is not evidence for
    another). Width knobs are GLOBAL; pass ``n_shards`` to translate for
    the per-shard runner."""
    from evotorch_tpu.observability.timings import resolve_knobs

    explicit = {
        "chunk_size": cfg["compact_chunk"] if cfg["compact_chunk_explicit"] else None,
        "min_width": cfg["compact_min_width"],
    }
    config, source = resolve_knobs(
        explicit,
        "compact",
        _tuned_shape(cfg, params, mesh_label),
        use_cache=_use_tuned_cache(cfg, params),
    )
    kwargs = {"chunk_size": int(config.get("chunk_size", cfg["compact_chunk"]))}
    if config.get("min_width") is not None:
        kwargs["min_width"] = max(1, int(config["min_width"]) // n_shards)
    return kwargs, source


def compact_kwargs(cfg: dict, *, n_shards: int = 1, params=None, mesh_label: str = "none") -> dict:
    """The kwargs half of :func:`tuned_compact` (kept for callers that
    don't report provenance)."""
    return tuned_compact(cfg, n_shards=n_shards, params=params, mesh_label=mesh_label)[0]


def tuned_refill(cfg: dict, *, n_shards: int = 1, params=None, mesh_label: str = "none"):
    """Lane-refill engine kwargs + ``tuned_config_source`` provenance —
    same precedence and cache key as :func:`tuned_compact`. The width
    knob is GLOBAL; pass ``n_shards`` to translate (flooring, like the
    other convenience knobs) for a per-shard sharded rollout."""
    from evotorch_tpu.observability.timings import resolve_knobs

    explicit = {
        "width": cfg["refill_width"],
        "period": cfg["refill_period"] if cfg["refill_period_explicit"] else None,
    }
    config, source = resolve_knobs(
        explicit,
        "refill",
        _tuned_shape(cfg, params, mesh_label),
        use_cache=_use_tuned_cache(cfg, params),
    )
    kwargs = {
        "refill_period": int(config.get("period") or cfg["refill_period"])
    }
    if config.get("width") is not None:
        kwargs["refill_width"] = max(1, int(config["width"]) // n_shards)
    return kwargs, source


def refill_kwargs(cfg: dict, *, n_shards: int = 1, params=None, mesh_label: str = "none") -> dict:
    """The kwargs half of :func:`tuned_refill` (kept for callers that
    don't report provenance)."""
    return tuned_refill(cfg, n_shards=n_shards, params=params, mesh_label=mesh_label)[0]


def tuned_policy(cfg: dict, *, params=None, mesh_label: str = "none"):
    """Trunk-delta policy-form knobs (``rank``, ``trunk_block``) +
    ``tuned_config_source`` provenance — same precedence and cache key as
    the schedule knobs, under the autotuner's ``policy`` group
    (observability/autotune.py ``PolicyHarness``). Fallback: rank 4 (the
    harness's cheapest candidate) and no lane blocking."""
    from evotorch_tpu.observability.timings import resolve_knobs

    explicit = {"rank": cfg["trunk_rank"], "trunk_block": cfg["trunk_block"]}
    config, source = resolve_knobs(
        explicit,
        "policy",
        _tuned_shape(cfg, params, mesh_label),
        use_cache=_use_tuned_cache(cfg, params),
    )
    return {
        "rank": int(config.get("rank") or 4),
        "trunk_block": int(config.get("trunk_block") or 0),
    }, source


def tuned_span(cfg: dict, *, params=None, mesh_label: str = "none"):
    """The fused-span length K + ``tuned_config_source`` provenance —
    same precedence and cache key as the schedule knobs, under the
    autotuner's ``span`` group (observability/autotune.py ``SpanHarness``).
    ``BENCH_SPAN=K`` overrides; ``BENCH_SPAN=auto`` consults the cache;
    fallback 8 (the acceptance shape's measured sweet spot)."""
    from evotorch_tpu.observability.timings import resolve_knobs

    raw = cfg["span"]
    explicit = {"span": None if raw in (None, "auto") else int(raw)}
    config, source = resolve_knobs(
        explicit,
        "span",
        _tuned_shape(cfg, params, mesh_label),
        use_cache=_use_tuned_cache(cfg, params),
    )
    return max(1, int(config.get("span") or 8)), source


def bench_hidden() -> list:
    """The BENCH_HIDDEN layer widths as a list of ints (default ``[64, 64]``)
    — also the ``hidden`` column bench.py stamps on ledger-carrying lines so
    bench_curves/ files are self-describing across policy-shape sweeps."""
    return [int(h) for h in os.environ.get("BENCH_HIDDEN", "64,64").split(",") if h]


def _bench_mlp(obs_dim: int, act_dim: int):
    """The BENCH_HIDDEN-sized MLP, shared by every bench policy builder so
    the bespoke-sim contracts, the real-MuJoCo A/B and the program ledger's
    gate programs cannot silently bench different architectures."""
    from evotorch_tpu.neuroevolution.net import tanh_mlp

    return tanh_mlp(obs_dim, act_dim, bench_hidden())


def build_policy(env):
    """The benchmark policy: an MLP sized by BENCH_HIDDEN (default "64,64" —
    the MXU-headroom knob; ES rollouts are env-bound, so the policy can grow
    orders of magnitude before it shows up in steps/s)."""
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy

    return FlatParamsPolicy(_bench_mlp(env.observation_size, env.action_size))


def measure_mujoco(cfg: dict) -> dict:
    """Real-MuJoCo host-path A/B: env-steps/sec of the PR-2 synchronous
    fixed-chunk loop vs the pipelined refill scheduler, same `MjVecEnv`,
    same population (aggressive random linear policies — the skewed
    episode-length regime evaluation actually sees at init). Returns the
    ``mj_*`` columns bench.py appends behind ``BENCH_BACKEND=mujoco``."""
    import time

    import gymnasium as gym
    import numpy as np

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy
    from evotorch_tpu.neuroevolution.net.hostvecenv import (
        run_host_pipelined_rollout,
        run_host_vectorized_rollout,
    )

    env_id = cfg["mj_env"]
    popsize = cfg["mj_popsize"]
    num_envs = cfg["mj_num_envs"]
    episode_length = cfg["mj_episode_length"]
    num_blocks = cfg["mj_blocks"]

    probe = gym.make(env_id)
    obs_dim = int(np.prod(probe.observation_space.shape))
    act_dim = int(np.prod(probe.action_space.shape))
    probe.close()
    policy = FlatParamsPolicy(_bench_mlp(obs_dim, act_dim))
    rng = np.random.default_rng(0)
    # numpy, NOT jnp: the rollout loops slice this matrix right before every
    # jitted forward dispatch, and a numpy argument is ~3x cheaper per
    # dispatch than a committed device array on this jax (CLAUDE.md r7 note)
    params = rng.normal(size=(popsize, policy.parameter_count)).astype(np.float32)

    def fresh_vec():
        vec = MjVecEnv(lambda: gym.make(env_id), num_envs)
        vec.seed(range(1000, 1000 + num_envs))
        return vec

    def run_sync_chunked(vec):
        total = 0
        for start in range(0, popsize, num_envs):
            result = run_host_vectorized_rollout(
                vec,
                policy,
                params[start : start + num_envs],
                num_episodes=1,
                episode_length=episode_length,
            )
            total += result["interactions"]
        return total

    def run_pipelined(vec):
        result = run_host_pipelined_rollout(
            vec,
            policy,
            params,
            num_episodes=1,
            episode_length=episode_length,
            mode="pipelined",
            num_blocks=num_blocks,
            # honor BENCH_TUNED=0 at this layer too: with it the measured
            # mj_* configs stay byte-compatible with pre-autotuner rounds
            use_tuned_cache=cfg["tuned"],
        )
        return result["interactions"]

    # warmup: compile every jit signature the TIMED runs will hit. The
    # gathered forward is keyed on the FULL (popsize, L) params shape, so the
    # pipelined warmup must pass the whole matrix; the chunked loop's forward
    # is keyed on chunk width, so warm the full chunk and (if popsize is not
    # a multiple of num_envs) the short final chunk too.
    vec = fresh_vec()
    run_host_vectorized_rollout(
        vec, policy, params[:num_envs], num_episodes=1, episode_length=3
    )
    if popsize % num_envs:
        run_host_vectorized_rollout(
            vec, policy, params[: popsize % num_envs], num_episodes=1, episode_length=3
        )
    run_host_pipelined_rollout(
        vec,
        policy,
        params,
        num_episodes=1,
        episode_length=3,
        mode="pipelined",
        num_blocks=num_blocks,
        use_tuned_cache=cfg["tuned"],
    )
    vec.close()

    out = {}
    repeats = cfg.get("mj_repeats", 1)
    for name, runner in (("sync", run_sync_chunked), ("pipelined", run_pipelined)):
        rates = []
        for _ in range(repeats):
            vec = fresh_vec()
            t0 = time.perf_counter()
            steps = runner(vec)
            elapsed = time.perf_counter() - t0
            vec.close()
            rates.append(steps / elapsed)
            print(
                f"[mujoco/{name}] {steps} env-steps in {elapsed:.2f}s "
                f"({steps / elapsed:.0f} steps/s)",
                file=sys.stderr,
            )
        out[name] = {"steps_per_sec": sorted(rates)[len(rates) // 2]}

    return {
        "mj_env": env_id,
        "mj_popsize": popsize,
        "mj_num_envs": num_envs,
        "mj_episode_length": episode_length,
        "mj_blocks": num_blocks,
        "mj_sync_steps_per_sec": round(out["sync"]["steps_per_sec"], 1),
        "mj_steps_per_sec": round(out["pipelined"]["steps_per_sec"], 1),
        "mj_pipeline_speedup": round(
            out["pipelined"]["steps_per_sec"] / out["sync"]["steps_per_sec"], 3
        ),
    }


def ledger_columns(record, *, steps_per_sec, steps_per_generation, param_count=None):
    """The per-contract program-ledger columns bench.py/bench_multichip.py
    append when BENCH_LEDGER is on. Nullable by design: a backend whose
    cost/memory analysis is unavailable emits nulls, never crashes
    (observability.programs guarded accessors).

    ``flops_per_step`` is the cost model's FLOPs per counted env-step — a
    program-cost fingerprint, NOT a utilization proxy: XLA's HloCostAnalysis
    counts a while-loop body ONCE (the rollout loop is undercounted by its
    trip count) while one-shot tensor work like a dense ask's (N, L)
    materialization is counted in full, so comparing policy FORMS on it
    inverts the truth. ``model_efficiency`` is therefore MFU-style: the
    achieved MODEL FLOP rate — 2 * param_count useful FLOPs per counted
    env-step (every lane-step runs the policy once; overhead and redundant
    work count AGAINST utilization) — over the nominal per-backend peak
    (EVOTORCH_PEAK_FLOPS overrides;
    observability.report.NOMINAL_PEAK_FLOPS documents the defaults). Needs
    ``param_count``; callers without it get a null column."""
    import jax

    from evotorch_tpu.observability.report import peak_flops

    flops_per_step = None
    if record.flops and steps_per_generation:
        flops_per_step = record.flops / steps_per_generation
    efficiency = None
    peak = peak_flops(jax.devices()[0].platform)
    if param_count and steps_per_sec and peak:
        efficiency = 2.0 * param_count * steps_per_sec / peak
    return {
        "compile_seconds": round(record.compile_seconds, 3),
        "flops_per_step": (
            None if flops_per_step is None else round(flops_per_step, 2)
        ),
        "peak_hbm_bytes": record.peak_bytes,
        "model_efficiency": (
            None if efficiency is None else round(efficiency, 6)
        ),
    }


def fresh_pgpe_state(parameter_count: int):
    import jax.numpy as jnp

    from evotorch_tpu.algorithms.functional import pgpe

    return pgpe(
        center_init=jnp.zeros(parameter_count, dtype=jnp.float32),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )
