"""Shared scaffolding for bench.py and bench_multichip.py: the TPU health
probe / CPU fallback dance, BENCH_* env-var parsing, and the policy builder —
one place, so the two benchmarks cannot silently diverge."""

import json
import os
import subprocess
import sys


def tpu_healthy() -> bool:
    """Probe backend init in a subprocess: the axon plugin can hang forever
    when its tunnel is unhealthy, which must not stall the benchmark driver."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            timeout=120,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def setup_backend() -> bool:
    """Pick TPU when the tunnel is healthy, else an 8-virtual-device CPU.
    Must run before jax's first device use. Returns use_cpu."""
    requested_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    use_cpu = requested_cpu or not tpu_healthy()
    if use_cpu:
        if not requested_cpu:
            print("TPU backend unhealthy; falling back to CPU", file=sys.stderr)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if use_cpu:
        jax.config.update("jax_platforms", "cpu")
    return use_cpu


def bench_config(use_cpu: bool, *, cpu_episode_length: int = 100) -> dict:
    """Parse the BENCH_* knobs (on the CPU fallback, defaults shrink so the
    benchmark cannot stall the driver)."""
    import jax.numpy as jnp

    return {
        "popsize": int(os.environ.get("BENCH_POPSIZE", 1024 if use_cpu else 10_000)),
        "episode_length": int(
            os.environ.get(
                "BENCH_EPISODE_LENGTH", cpu_episode_length if use_cpu else 200
            )
        ),
        "generations": int(os.environ.get("BENCH_GENERATIONS", 3)),
        # opt-in bf16: changes the measured compute dtype, so the default
        # stays comparable with previously recorded f32 baselines
        "compute_dtype": (
            jnp.bfloat16 if os.environ.get("BENCH_BF16", "0") == "1" else None
        ),
        "eval_mode": os.environ.get("BENCH_EVAL_MODE", "budget"),
        # BENCH_LOWRANK=k: evaluate a low-rank-structured population of rank k
        # (the MXU path for wide policies, net/lowrank.py); 0 = dense
        "lowrank": int(os.environ.get("BENCH_LOWRANK", "0")),
        "env_name": os.environ.get("BENCH_ENV", "humanoid"),
        "env_kwargs": json.loads(os.environ.get("BENCH_ENV_ARGS", "{}")),
        # lane-compaction tuning (episodes_compact only): chunk size between
        # host width-decisions, and the width-menu floor — the knobs to sweep
        # on real hardware (BENCH_NOTES.md)
        "compact_chunk": int(os.environ.get("BENCH_COMPACT_CHUNK", "25")),
        "compact_min_width": (
            int(os.environ["BENCH_COMPACT_MINWIDTH"])
            if "BENCH_COMPACT_MINWIDTH" in os.environ
            else None
        ),
        # lane-refill tuning (episodes_refill only): the fixed lane width W
        # (default: engine picks ~work/8) and the refill period (refill every
        # k-th step; >1 amortizes the refill gather/reset at the cost of
        # finished lanes idling up to k-1 steps)
        "refill_width": (
            int(os.environ["BENCH_REFILL_WIDTH"])
            if "BENCH_REFILL_WIDTH" in os.environ
            else None
        ),
        "refill_period": int(os.environ.get("BENCH_REFILL_PERIOD", "1")),
    }


def compact_kwargs(cfg: dict, *, n_shards: int = 1) -> dict:
    """Lane-compaction runner kwargs from the BENCH knobs — one place for
    both benches. Width knobs are GLOBAL; pass ``n_shards`` to translate for
    the per-shard sharded runner."""
    kwargs = {"chunk_size": cfg["compact_chunk"]}
    if cfg["compact_min_width"] is not None:
        kwargs["min_width"] = max(1, cfg["compact_min_width"] // n_shards)
    return kwargs


def refill_kwargs(cfg: dict, *, n_shards: int = 1) -> dict:
    """Lane-refill engine kwargs from the BENCH knobs. The width knob is
    GLOBAL; pass ``n_shards`` to translate (flooring, like the other
    convenience knobs) for a per-shard sharded rollout."""
    kwargs = {"refill_period": cfg["refill_period"]}
    if cfg["refill_width"] is not None:
        kwargs["refill_width"] = max(1, cfg["refill_width"] // n_shards)
    return kwargs


def build_policy(env):
    """The benchmark policy: an MLP sized by BENCH_HIDDEN (default "64,64" —
    the MXU-headroom knob; ES rollouts are env-bound, so the policy can grow
    orders of magnitude before it shows up in steps/s)."""
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh

    hidden = [int(h) for h in os.environ.get("BENCH_HIDDEN", "64,64").split(",") if h]
    net = Linear(env.observation_size, hidden[0])
    for a, b in zip(hidden, hidden[1:] + [None]):
        net = net >> Tanh()
        net = net >> Linear(a, b if b is not None else env.action_size)
    return FlatParamsPolicy(net)


def fresh_pgpe_state(parameter_count: int):
    import jax.numpy as jnp

    from evotorch_tpu.algorithms.functional import pgpe

    return pgpe(
        center_init=jnp.zeros(parameter_count, dtype=jnp.float32),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )
